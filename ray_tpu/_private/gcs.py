"""Global control state (GCS).

Analog of the reference's GCS server state (src/ray/gcs/gcs_server/
gcs_server.h:79): internal KV (gcs_kv_manager.h), the function/class
table, named actors + the actor location directory
(gcs_actor_manager.h:308), node membership & resource views
(gcs_node_manager.h:45, gcs_resource_manager.h:59), and the object
location directory (the reference resolves locations through owners,
ownership_based_object_directory.cc — here the GCS holds them directly,
a deliberate simplification that keeps the pull path one hop).

Single-node deployments embed this in the head node service; multi-node
clusters serve the same object over TCP via gcs_service.GcsServer.
All methods are thread-safe.  Pubsub: `sub_*` callbacks fire inline
under no lock contention guarantees beyond per-call atomicity.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple


class NodeInfo:
    __slots__ = ("node_id", "host", "control_port", "transfer_port",
                 "resources_total", "resources_avail", "last_heartbeat",
                 "state", "load", "drain_deadline", "drain_reason")

    def __init__(self, node_id: bytes, host: str, control_port: int,
                 transfer_port: int, resources_total: Dict[str, float]
                 ) -> None:
        self.node_id = node_id
        self.host = host
        self.control_port = control_port
        self.transfer_port = transfer_port
        self.resources_total = dict(resources_total)
        self.resources_avail = dict(resources_total)
        self.last_heartbeat = time.time()
        # alive | draining | dead.  "draining" is a first-class
        # lifecycle state (planned departure: operator drain or a TPU
        # preemption notice): the node is still reachable and serving,
        # but schedulers must stop routing NEW work to it and it will
        # transition to dead — cleanly (it hands back work, migrates
        # actors, re-replicates sole object copies, then reports
        # itself drained) or via the drain-deadline health check.
        self.state = "alive"
        # Wall-clock deadline by which a draining node must be gone
        # (preemption deadline / drain grace); None while alive.
        self.drain_deadline: Optional[float] = None
        self.drain_reason = ""
        # Scheduling load from the node's last heartbeat (autoscaler
        # demand signal): {"pending": N, "shapes": [resource dicts],
        # "idle_since": ts | None}.
        self.load: Dict[str, object] = {}

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "host": self.host,
                "control_port": self.control_port,
                "transfer_port": self.transfer_port,
                "resources_total": dict(self.resources_total),
                "resources_avail": dict(self.resources_avail),
                "state": self.state, "load": dict(self.load),
                "drain_deadline": self.drain_deadline,
                "drain_reason": self.drain_reason}


class GlobalControlState:
    """In-memory control-plane tables, optionally durable.

    `persist_dir` enables the reference's GCS-FT role
    (gcs/store_client/redis_store_client.h:106, swapped for a local
    write-ahead log): every DURABLE mutation (KV, function table, named
    actors) appends one pickled op to `gcs.wal`, replayed by the next
    GlobalControlState pointed at the same directory — so detached-actor
    names, job records, and workflow/meta KV survive a GCS restart.
    Node membership and object locations are deliberately ephemeral:
    nodes re-register and re-report on reconnect, exactly like the
    reference's restarted GCS rebuilding from raylet resubscription."""

    # KV namespaces worth durability.  High-frequency transient channels
    # (tune/train report queues, collective rendezvous boards) would
    # otherwise grow the WAL without bound — a put+del pair per report,
    # never compacted.
    DURABLE_KV_NS = ("jobs", "default", "serve")

    def __init__(self, persist_dir: Optional[str] = None,
                 durable_kv_namespaces: Optional[Tuple[str, ...]] = None
                 ) -> None:
        self._durable_ns = tuple(durable_kv_namespaces
                                 or self.DURABLE_KV_NS)
        self._lock = threading.RLock()
        self._kv: Dict[str, Dict[bytes, bytes]] = {}
        self._functions: Dict[bytes, bytes] = {}
        self._named_actors: Dict[str, bytes] = {}  # "ns/name" -> actor_id
        # -- multi-node tables --
        self._nodes: Dict[bytes, NodeInfo] = {}
        # oid -> (set of node_ids holding a copy, size)
        self._locations: Dict[bytes, Tuple[Set[bytes], int]] = {}
        # oid -> (kind, data) for small payloads the GCS can hand out
        # directly: "inline" values and serialized errors.
        self._small_objects: Dict[bytes, Tuple[str, bytes]] = {}
        self._actor_nodes: Dict[bytes, bytes] = {}  # actor_id -> node_id
        # Objects whose ONLY copies died with a node: the record is
        # gone, but "it was once READY" is the bit an owner needs to
        # tell completed-then-lost (reconstruct from lineage) apart
        # from never-ran (retry/fail by task policy).  Cleared when a
        # reconstruction republishes or the owner deletes the object.
        self._lost_objects: Set[bytes] = set()
        # subscriptions (server wires these to connection pushes)
        self._loc_subs: Dict[bytes, List[Callable[[bytes, dict], None]]] = {}
        # kv_wait parking: (ns, key) -> callbacks fired on the next put
        # (the long-poll primitive process collectives block on instead
        # of 2ms polling; reference: pubsub long-poll, src/ray/pubsub/)
        self._kv_waiters: Dict[tuple, List[Callable[[bytes], None]]] = {}
        self._node_subs: List[Callable[[str, dict], None]] = []
        self._wal = None
        if persist_dir:
            import os
            import pickle
            os.makedirs(persist_dir, exist_ok=True)
            path = os.path.join(persist_dir, "gcs.wal")
            good_end = 0
            if os.path.exists(path):
                with open(path, "rb") as f:
                    while True:
                        try:
                            op, args = pickle.load(f)
                        except EOFError:
                            good_end = f.tell()
                            break
                        except Exception:
                            # Torn tail write (crash mid-append): keep
                            # the good prefix only.  Appending AFTER the
                            # garbage would make every later record
                            # unreachable to the next replay.
                            break
                        good_end = f.tell()
                        self._replay(op, args)
                size = os.path.getsize(path)
                if good_end < size:
                    with open(path, "r+b") as f:
                        f.truncate(good_end)
            self._wal = open(path, "ab")

    def _replay(self, op: str, args: tuple) -> None:
        if op == "kv_put":
            ns, key, value = args
            self._kv.setdefault(ns, {})[key] = value
        elif op == "kv_del":
            ns, key = args
            self._kv.get(ns, {}).pop(key, None)
        elif op == "fn":
            self._functions[args[0]] = args[1]
        elif op == "actor_put":
            self._named_actors[args[0]] = args[1]
        elif op == "actor_del":
            self._named_actors.pop(args[0], None)

    def _log(self, op: str, *args) -> None:
        """Append one durable op.  Caller holds the lock."""
        if self._wal is None:
            return
        import pickle
        pickle.dump((op, args), self._wal)
        self._wal.flush()

    # -- internal KV -------------------------------------------------------
    def kv_put(self, ns: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        with self._lock:
            table = self._kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            if ns in self._durable_ns:
                self._log("kv_put", ns, key, value)
            waiters = self._kv_waiters.pop((ns, key), [])
        for cb in waiters:          # outside the lock: cbs do IO
            try:
                cb(value)
            except Exception:
                pass
        return True

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(ns, {}).get(key)

    def kv_del(self, ns: str, key: bytes) -> bool:
        with self._lock:
            hit = self._kv.get(ns, {}).pop(key, None) is not None
            if hit and ns in self._durable_ns:
                self._log("kv_del", ns, key)
            return hit

    def kv_wait_register(self, ns: str, key: bytes,
                         cb: Callable[[bytes], None]
                         ) -> Optional[bytes]:
        """Return the value if present, else park `cb` for the next
        kv_put of this key."""
        with self._lock:
            v = self._kv.get(ns, {}).get(key)
            if v is not None:
                return v
            self._kv_waiters.setdefault((ns, key), []).append(cb)
            return None

    def kv_wait_unregister(self, ns: str, key: bytes, cb) -> None:
        with self._lock:
            lst = self._kv_waiters.get((ns, key))
            if lst is not None:
                try:
                    lst.remove(cb)
                except ValueError:
                    pass
                if not lst:
                    del self._kv_waiters[(ns, key)]

    def kv_keys(self, ns: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    # -- function table ----------------------------------------------------
    def register_function(self, function_id: bytes, blob: bytes) -> None:
        with self._lock:
            self._functions[function_id] = blob
            self._log("fn", function_id, blob)

    def fetch_function(self, function_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._functions.get(function_id)

    # -- named actors ------------------------------------------------------
    def register_named_actor(self, ns: str, name: str,
                             actor_id: bytes) -> bool:
        with self._lock:
            key = f"{ns}/{name}"
            if key in self._named_actors:
                return False
            self._named_actors[key] = actor_id
            self._log("actor_put", key, actor_id)
            return True

    def lookup_named_actor(self, ns: str, name: str) -> Optional[bytes]:
        with self._lock:
            return self._named_actors.get(f"{ns}/{name}")

    def drop_named_actor(self, actor_id: bytes) -> None:
        with self._lock:
            dead = [k for k, v in self._named_actors.items() if v == actor_id]
            for k in dead:
                del self._named_actors[k]
                self._log("actor_del", k)

    def list_named_actors(self, ns: Optional[str] = None) -> List[str]:
        with self._lock:
            if ns is None:
                return list(self._named_actors)
            return [k.split("/", 1)[1] for k in self._named_actors
                    if k.startswith(ns + "/")]

    # -- node membership & resources (gcs_node_manager.h:45) ---------------
    def register_node(self, node_id: bytes, host: str, control_port: int,
                      transfer_port: int,
                      resources_total: Dict[str, float]) -> None:
        with self._lock:
            self._nodes[node_id] = NodeInfo(
                node_id, host, control_port, transfer_port, resources_total)
        self._publish_node("node_added", self._nodes[node_id].to_dict())

    def heartbeat(self, node_id: bytes,
                  resources_avail: Dict[str, float],
                  load: Optional[dict] = None) -> None:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or n.state == "dead":
                return
            # Draining nodes keep heartbeating while they hand off
            # work; a heartbeat must NOT resurrect them to "alive" —
            # only last_heartbeat/resources update, the state machine
            # moves forward exclusively (alive -> draining -> dead).
            n.last_heartbeat = time.time()
            n.resources_avail = dict(resources_avail)
            if load is not None:
                n.load = dict(load)

    def drain_node(self, node_id: bytes, grace_s: float = 30.0,
                   reason: str = "drain requested") -> bool:
        """Begin a graceful departure: alive -> draining, published as
        a `node_draining` event (the node itself reacts by handing
        back queued work, migrating actors, and re-replicating sole
        object copies; peers stop targeting it).  Returns False for an
        unknown, already-draining, or dead node — the transition fires
        exactly once."""
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or n.state != "alive":
                return False
            n.state = "draining"
            n.drain_deadline = time.time() + max(grace_s, 0.0)
            n.drain_reason = reason
            info = n.to_dict()
        info["reason"] = reason
        info["grace_s"] = max(grace_s, 0.0)
        self._publish_node("node_draining", info)
        return True

    def mark_node_dead(self, node_id: bytes, reason: str = "") -> None:
        lost_notifies = []
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or n.state == "dead":
                # Drain/death race guard: a drained node reports itself
                # dead AND the health check may fire on it — whichever
                # call runs second sees "dead" here and returns, so the
                # node_dead actor/object cleanup publishes exactly once.
                return
            n.state = "dead"
            # Copies on a dead node are gone.  Subscribers waiting on an
            # object whose LAST copy just vanished must hear about it
            # (kind="lost") or they would block forever.
            for oid in list(self._locations):
                holders, size = self._locations[oid]
                holders.discard(node_id)
                if not holders and oid not in self._small_objects:
                    del self._locations[oid]
                    self._lost_objects.add(oid)
                    subs = self._loc_subs.pop(oid, [])
                    if subs:
                        lost_notifies.append((oid, size, subs))
            dead_actors = [a for a, nid in self._actor_nodes.items()
                           if nid == node_id]
            for a in dead_actors:
                del self._actor_nodes[a]
                self.drop_named_actor(a)
            info = n.to_dict()
        for oid, size, subs in lost_notifies:
            evt = {"object_id": oid, "node_id": None, "size": size,
                   "kind": "lost"}
            for cb in subs:
                try:
                    cb(oid, evt)
                except Exception:
                    pass
        info["reason"] = reason
        info["dead_actors"] = dead_actors
        self._publish_node("node_dead", info)

    def nodes(self, alive_only: bool = True) -> List[dict]:
        """alive_only means "not dead": draining nodes are still
        reachable and still serving (objects pull from them, their
        actors answer until migrated), so they stay in the cluster
        view — consumers that must not target them filter on
        state == "alive" (spill targets, placement, feasibility)."""
        with self._lock:
            return [n.to_dict() for n in self._nodes.values()
                    if not alive_only or n.state != "dead"]

    def node_info(self, node_id: bytes) -> Optional[dict]:
        with self._lock:
            n = self._nodes.get(node_id)
            return n.to_dict() if n else None

    def check_health(self, timeout_s: float) -> List[dict]:
        """Mark nodes with stale heartbeats dead; returns newly-dead.

        Draining nodes get their drain-grace deadline instead of the
        plain heartbeat timeout: heartbeats naturally stop while a
        node finishes its drain sequence and exits, so silence alone
        is not death until the deadline has passed (a cleanly drained
        node reports itself dead before that)."""
        now = time.time()
        with self._lock:
            stale = []
            for n in self._nodes.values():
                hb_stale = now - n.last_heartbeat > timeout_s
                if n.state == "alive" and hb_stale:
                    stale.append((n.node_id, "missed heartbeats"))
                elif n.state == "draining" and hb_stale:
                    # Heartbeats continue THROUGH a drain (a clean exit
                    # reports itself dead), so silence during one means
                    # either the final exit race (give it the deadline)
                    # or a hard crash mid-drain — a long grace must not
                    # hide a dead node for minutes, so extended silence
                    # (3x the plain timeout) reaps it regardless.
                    if now > (n.drain_deadline or 0.0):
                        stale.append((n.node_id,
                                      "drain deadline exceeded "
                                      f"({n.drain_reason or 'drain'})"))
                    elif now - n.last_heartbeat > 3 * timeout_s:
                        stale.append((n.node_id,
                                      "crashed while draining "
                                      "(missed heartbeats)"))
        newly_dead = []
        for nid, reason in stale:
            self.mark_node_dead(nid, reason)
            newly_dead.append(self.node_info(nid))
        return newly_dead

    # -- object locations --------------------------------------------------
    def add_location(self, oid: bytes, node_id: Optional[bytes], size: int,
                     kind: str = "shm", data: Optional[bytes] = None
                     ) -> None:
        """Register a copy.  kind 'inline'/'error' payloads ride in the
        GCS record itself (small by construction) so readers skip the
        node-to-node pull."""
        with self._lock:
            holders, _ = self._locations.get(oid, (set(), 0))
            if node_id is not None:
                holders.add(node_id)
            self._locations[oid] = (holders, size)
            self._lost_objects.discard(oid)
            if kind in ("inline", "error") and data is not None:
                self._small_objects[oid] = (kind, data)
            subs = list(self._loc_subs.get(oid, ()))
        evt = {"object_id": oid, "node_id": node_id, "size": size,
               "kind": kind}
        for cb in subs:
            try:
                cb(oid, evt)
            except Exception:
                pass

    def get_locations(self, oid: bytes) -> dict:
        with self._lock:
            holders, size = self._locations.get(oid, (set(), 0))
            small = self._small_objects.get(oid)
            # Draining holders stay fetchable: their copies are valid
            # until the node actually exits (and the drain re-replicates
            # sole copies elsewhere before that).
            alive = [self._nodes[h].to_dict() for h in holders
                     if h in self._nodes
                     and self._nodes[h].state != "dead"]
            lost = oid in self._lost_objects
        out = {"nodes": alive, "size": size}
        if small is not None:
            out["kind"], out["data"] = small
        else:
            out["kind"] = "shm" if alive else None
            if out["kind"] is None and lost:
                out["lost"] = True      # once READY; copies died
        return out

    def remove_object(self, oid: bytes) -> List[bytes]:
        """Owner-driven delete: drop the record; returns holder node ids
        (the server publishes object_deleted to them).  Subscribers
        still pulling hear kind='lost' so their pull loops terminate
        instead of polling a vanished record forever."""
        with self._lock:
            holders, size = self._locations.pop(oid, (set(), 0))
            self._small_objects.pop(oid, None)
            self._lost_objects.discard(oid)
            subs = self._loc_subs.pop(oid, [])
        evt = {"object_id": oid, "node_id": None, "size": size,
               "kind": "lost"}
        for cb in subs:
            try:
                cb(oid, evt)
            except Exception:
                pass
        return list(holders)

    def remove_location(self, oid: bytes, node_id: bytes) -> None:
        """Drop one node from an object's holder set (replica freed or
        observed missing); the record itself stays."""
        with self._lock:
            entry = self._locations.get(oid)
            if entry is None:
                return
            entry[0].discard(node_id)

    def sub_location(self, oid: bytes,
                     cb: Callable[[bytes, dict], None]) -> None:
        fire = None
        with self._lock:
            if oid in self._locations or oid in self._small_objects:
                holders, size = self._locations.get(oid, (set(), 0))
                small = self._small_objects.get(oid)
                if small is not None:
                    fire = {"object_id": oid, "node_id": None,
                            "size": size, "kind": small[0]}
                elif holders:
                    fire = {"object_id": oid,
                            "node_id": next(iter(holders)),
                            "size": size, "kind": "shm"}
            self._loc_subs.setdefault(oid, []).append(cb)
        if fire is not None:
            cb(oid, fire)

    def unsub_location(self, oid: bytes, cb) -> None:
        with self._lock:
            subs = self._loc_subs.get(oid)
            if subs and cb in subs:
                subs.remove(cb)
                if not subs:
                    del self._loc_subs[oid]

    # -- actor directory ---------------------------------------------------
    def set_actor_node(self, actor_id: bytes, node_id: bytes) -> None:
        with self._lock:
            self._actor_nodes[actor_id] = node_id

    def get_actor_node(self, actor_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._actor_nodes.get(actor_id)

    def drop_actor(self, actor_id: bytes) -> None:
        with self._lock:
            self._actor_nodes.pop(actor_id, None)
        self.drop_named_actor(actor_id)

    # -- node event pubsub -------------------------------------------------
    def sub_nodes(self, cb: Callable[[str, dict], None]) -> None:
        with self._lock:
            self._node_subs.append(cb)

    def unsub_nodes(self, cb) -> None:
        with self._lock:
            if cb in self._node_subs:
                self._node_subs.remove(cb)

    def _publish_node(self, event: str, info: dict) -> None:
        with self._lock:
            subs = list(self._node_subs)
        for cb in subs:
            try:
                cb(event, info)
            except Exception:
                pass
