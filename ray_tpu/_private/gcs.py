"""Global control state (GCS).

Analog of the reference's GCS server state (src/ray/gcs/gcs_server/
gcs_server.h:79): internal KV (gcs_kv_manager.h), the function/class
table, named actors + the actor location directory
(gcs_actor_manager.h:308), node membership & resource views
(gcs_node_manager.h:45, gcs_resource_manager.h:59), and the object
location directory (the reference resolves locations through owners,
ownership_based_object_directory.cc — here the GCS holds them directly,
a deliberate simplification that keeps the pull path one hop).

Single-node deployments embed this in the head node service; multi-node
clusters serve the same object over TCP via gcs_service.GcsServer.
All methods are thread-safe.  Pubsub: `sub_*` callbacks fire inline
under no lock contention guarantees beyond per-call atomicity.

Durability split (GCS fault tolerance — reference: Ray HA GCS over
external Redis, gcs/store_client/redis_store_client.h:106):

* HARD state goes to the write-ahead log and survives `kill -9`:
  durable KV namespaces, the function table, named actors, node
  registrations (including an in-progress drain and its deadline),
  the actor -> node directory, inline/error small-object payloads,
  and lost-object markers.
* SOFT state is deliberately NOT logged and is rebuilt by node
  re-sync after a restart: shm object locations, heartbeats /
  resource views, pubsub subscriptions, and kv-wait parking — exactly
  like the reference's restarted GCS rebuilding from raylet
  resubscription.

Every construction against a persist_dir begins a new *recovery
epoch* (stamped on every server reply): nodes that observe the bump —
or simply reconnect — re-register and bulk re-publish their
authoritative local state via ``resync_node``.  Until a recovered
node re-syncs, its last-known record is served tagged ``stale``
rather than dropped, and the health check gives it
``gcs_resync_grace_s`` instead of the plain heartbeat timeout.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.config import config

# WAL ops that fsync immediately (when gcs_wal_fsync is on): acked
# control-plane transitions whose loss would strand a caller that saw
# the ack.  Hot-path ops (kv churn, forwarded small results) batch
# into one fsync per gcs_wal_fsync_batch_s window instead.
_FSYNC_CRITICAL_OPS = frozenset((
    "actor_put", "actor_del", "node_reg", "node_drain", "node_dead",
    "epoch"))

_SNAPSHOT_VERSION = 1


class NodeInfo:
    __slots__ = ("node_id", "host", "control_port", "transfer_port",
                 "resources_total", "resources_avail", "last_heartbeat",
                 "state", "load", "drain_deadline", "drain_reason",
                 "stale")

    def __init__(self, node_id: bytes, host: str, control_port: int,
                 transfer_port: int, resources_total: Dict[str, float]
                 ) -> None:
        self.node_id = node_id
        self.host = host
        self.control_port = control_port
        self.transfer_port = transfer_port
        self.resources_total = dict(resources_total)
        self.resources_avail = dict(resources_total)
        self.last_heartbeat = time.time()
        # alive | draining | dead.  "draining" is a first-class
        # lifecycle state (planned departure: operator drain or a TPU
        # preemption notice): the node is still reachable and serving,
        # but schedulers must stop routing NEW work to it and it will
        # transition to dead — cleanly (it hands back work, migrates
        # actors, re-replicates sole object copies, then reports
        # itself drained) or via the drain-deadline health check.
        self.state = "alive"
        # Wall-clock deadline by which a draining node must be gone
        # (preemption deadline / drain grace); None while alive.
        self.drain_deadline: Optional[float] = None
        self.drain_reason = ""
        # Scheduling load from the node's last heartbeat (autoscaler
        # demand signal): {"pending": N, "shapes": [resource dicts],
        # "idle_since": ts | None}.
        self.load: Dict[str, object] = {}
        # True for a record recovered from the WAL/snapshot after a GCS
        # restart that the node has not yet re-confirmed via resync:
        # served (locations, actor homes, cluster views keep working on
        # last-known data) but tagged, and reaped by the health check
        # only after gcs_resync_grace_s.
        self.stale = False

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "host": self.host,
                "control_port": self.control_port,
                "transfer_port": self.transfer_port,
                "resources_total": dict(self.resources_total),
                "resources_avail": dict(self.resources_avail),
                "state": self.state, "load": dict(self.load),
                "drain_deadline": self.drain_deadline,
                "drain_reason": self.drain_reason,
                "stale": self.stale}


class GlobalControlState:
    """In-memory control-plane tables, optionally durable.

    `persist_dir` enables the reference's GCS-FT role: every HARD
    mutation appends one pickled op to `gcs.wal` (fsync policy:
    `gcs_wal_fsync`), periodically folded into a `gcs.snap` full-state
    snapshot with the log truncated (compaction) so the WAL stops
    growing unbounded.  The next GlobalControlState pointed at the same
    directory replays snapshot + log — so detached-actor names, node
    membership, the actor directory, and inline results survive a GCS
    `kill -9`.  See the module docstring for the hard/soft split."""

    # KV namespaces worth durability.  High-frequency transient channels
    # (tune/train report queues, collective rendezvous boards) would
    # otherwise grow the WAL without bound — a put+del pair per report,
    # never compacted.
    DURABLE_KV_NS = ("jobs", "default", "serve")

    def __init__(self, persist_dir: Optional[str] = None,
                 durable_kv_namespaces: Optional[Tuple[str, ...]] = None
                 ) -> None:
        self._durable_ns = tuple(durable_kv_namespaces
                                 or self.DURABLE_KV_NS)
        self._lock = threading.RLock()
        self._kv: Dict[str, Dict[bytes, bytes]] = {}
        self._functions: Dict[bytes, bytes] = {}
        self._named_actors: Dict[str, bytes] = {}  # "ns/name" -> actor_id
        # -- multi-node tables --
        self._nodes: Dict[bytes, NodeInfo] = {}
        # oid -> (set of node_ids holding a copy, size)
        self._locations: Dict[bytes, Tuple[Set[bytes], int]] = {}
        # oid -> (kind, data) for small payloads the GCS can hand out
        # directly: "inline" values and serialized errors.
        self._small_objects: Dict[bytes, Tuple[str, bytes]] = {}
        self._actor_nodes: Dict[bytes, bytes] = {}  # actor_id -> node_id
        # Objects whose ONLY copies died with a node: the record is
        # gone, but "it was once READY" is the bit an owner needs to
        # tell completed-then-lost (reconstruct from lineage) apart
        # from never-ran (retry/fail by task policy).  Cleared when a
        # reconstruction republishes or the owner deletes the object.
        self._lost_objects: Set[bytes] = set()
        # subscriptions (server wires these to connection pushes)
        self._loc_subs: Dict[bytes, List[Callable[[bytes, dict], None]]] = {}
        # kv_wait parking: (ns, key) -> callbacks fired on the next put
        # (the long-poll primitive process collectives block on instead
        # of 2ms polling; reference: pubsub long-poll, src/ray/pubsub/)
        self._kv_waiters: Dict[tuple, List[Callable[[bytes], None]]] = {}
        self._node_subs: List[Callable[[str, dict], None]] = []
        # Recovery epoch: bumps once per construction-with-persistence,
        # stamped on every server reply so clients detect a restart
        # even when their TCP reconnect raced the outage.  Epoch 1 = a
        # fresh (or non-durable) control plane.
        self.epoch = 1
        self.started = time.time()
        # Wall time of the last WAL/snapshot recovery (None = clean
        # first boot): anchors the resync grace for stale records.
        self._recovered_ts: Optional[float] = None
        self._wal = None
        self._wal_path: Optional[str] = None
        self._snap_path: Optional[str] = None
        # Embedded op telemetry: how the control plane is being used
        # (kv traffic vs object-directory traffic vs membership),
        # surfaced as "op_counts" in status() — works in-process too,
        # where the GcsServer dispatch wrapper never runs.
        self._op_counts: Dict[str, int] = {}
        self._wal_ops = 0               # records since the last snapshot
        self._last_fsync = 0.0
        self._last_snapshot_ts: Optional[float] = None
        # Backoff after a FAILED snapshot (e.g. disk full): without it
        # the still-exceeded compaction thresholds would re-attempt a
        # full-state dump on every subsequent durable mutation.
        self._next_snapshot_try = 0.0
        if persist_dir:
            self._open_persistence(persist_dir)

    # -- durability: snapshot + WAL ----------------------------------------
    def _open_persistence(self, persist_dir: str) -> None:
        os.makedirs(persist_dir, exist_ok=True)
        self._wal_path = os.path.join(persist_dir, "gcs.wal")
        self._snap_path = os.path.join(persist_dir, "gcs.snap")
        recovered_epoch = 0
        had_state = False
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    snap = pickle.load(f)
                recovered_epoch = self._load_snapshot(snap)
                had_state = True
            except Exception:
                # A torn snapshot (crash mid-replace should be
                # impossible with os.replace, but a truncated disk is
                # not): fall back to whatever the WAL holds.
                pass
        if os.path.exists(self._wal_path):
            good_end = 0
            with open(self._wal_path, "rb") as f:
                while True:
                    try:
                        op, args = pickle.load(f)
                    except EOFError:
                        good_end = f.tell()
                        break
                    except Exception:
                        # Torn tail write (crash mid-append): keep
                        # the good prefix only.  Appending AFTER the
                        # garbage would make every later record
                        # unreachable to the next replay.
                        break
                    good_end = f.tell()
                    if op == "epoch":
                        recovered_epoch = max(recovered_epoch,
                                              int(args[0]))
                    else:
                        self._replay(op, args)
                    had_state = True
            size = os.path.getsize(self._wal_path)
            if good_end < size:
                with open(self._wal_path, "r+b") as f:
                    f.truncate(good_end)
        self.epoch = recovered_epoch + 1
        if had_state:
            self._recovered_ts = time.time()
            # Recovered non-dead nodes are last-known, not confirmed:
            # tag stale and restart their heartbeat clock so the health
            # check gives them the resync grace instead of reaping them
            # for silence that happened while the GCS itself was down.
            for n in self._nodes.values():
                if n.state != "dead":
                    n.stale = True
                    n.last_heartbeat = self._recovered_ts
        self._wal = open(self._wal_path, "ab")
        self._log("epoch", self.epoch)

    def _load_snapshot(self, snap: dict) -> int:
        self._kv = {ns: dict(t) for ns, t in snap.get("kv", {}).items()}
        self._functions = dict(snap.get("functions", {}))
        self._named_actors = dict(snap.get("named_actors", {}))
        self._actor_nodes = dict(snap.get("actor_nodes", {}))
        self._small_objects = dict(snap.get("small_objects", {}))
        self._lost_objects = set(snap.get("lost_objects", ()))
        for nd in snap.get("nodes", ()):
            n = NodeInfo(nd["node_id"], nd["host"], nd["control_port"],
                         nd["transfer_port"], nd["resources_total"])
            n.state = nd.get("state", "alive")
            n.drain_deadline = nd.get("drain_deadline")
            n.drain_reason = nd.get("drain_reason", "")
            self._nodes[n.node_id] = n
        return int(snap.get("epoch", 0))

    def _replay(self, op: str, args: tuple) -> None:
        if op == "kv_put":
            ns, key, value = args
            self._kv.setdefault(ns, {})[key] = value
        elif op == "kv_del":
            ns, key = args
            self._kv.get(ns, {}).pop(key, None)
        elif op == "fn":
            self._functions[args[0]] = args[1]
        elif op == "actor_put":
            self._named_actors[args[0]] = args[1]
        elif op == "actor_del":
            self._named_actors.pop(args[0], None)
        elif op == "node_reg":
            node_id, host, cp, tp, res = args
            self._nodes[node_id] = NodeInfo(node_id, host, cp, tp, res)
        elif op == "node_drain":
            node_id, deadline, reason = args
            n = self._nodes.get(node_id)
            if n is not None and n.state != "dead":
                n.state = "draining"
                n.drain_deadline = deadline
                n.drain_reason = reason
        elif op == "node_dead":
            n = self._nodes.get(args[0])
            if n is not None:
                n.state = "dead"
            for aid in [a for a, nid in self._actor_nodes.items()
                        if nid == args[0]]:
                del self._actor_nodes[aid]
        elif op == "actor_node":
            self._actor_nodes[args[0]] = args[1]
        elif op == "actor_node_del":
            self._actor_nodes.pop(args[0], None)
        elif op == "small_obj":
            oid, kind, data = args
            self._small_objects[oid] = (kind, data)
        elif op == "small_obj_del":
            self._small_objects.pop(args[0], None)
        elif op == "lost_add":
            self._lost_objects.add(args[0])
        elif op == "lost_del":
            self._lost_objects.discard(args[0])

    def _count_op(self, name: str) -> None:
        """Bump one op-usage counter.  Caller holds the lock."""
        self._op_counts[name] = self._op_counts.get(name, 0) + 1

    def _log(self, op: str, *args) -> None:
        """Append one durable op.  Caller holds the lock."""
        if self._wal is None:
            return
        pickle.dump((op, args), self._wal)
        self._wal.flush()
        if config.gcs_wal_fsync:
            now = time.monotonic()
            if (op in _FSYNC_CRITICAL_OPS
                    or now - self._last_fsync
                    >= config.gcs_wal_fsync_batch_s):
                try:
                    os.fsync(self._wal.fileno())
                except OSError:
                    pass
                self._last_fsync = now
        self._wal_ops += 1
        self._maybe_compact_locked()

    def _maybe_compact_locked(self) -> None:
        if self._wal is None:
            return
        try:
            wal_bytes = self._wal.tell()
        except (OSError, ValueError):
            return
        if (self._wal_ops < config.gcs_wal_compact_ops
                and wal_bytes < config.gcs_wal_compact_bytes):
            return
        if time.monotonic() < self._next_snapshot_try:
            return      # last snapshot failed; don't retry per-append
        self.snapshot()

    def snapshot(self) -> None:
        """Fold the full hard state into `gcs.snap` and truncate the
        WAL (log compaction).  Crash-safe: the snapshot is written to a
        temp file, fsynced, and atomically renamed BEFORE the log is
        truncated — a crash between the two replays snapshot + old log,
        which is idempotent (replay ops are last-writer-wins)."""
        with self._lock:
            if self._wal is None or self._snap_path is None:
                return
            snap = {
                "version": _SNAPSHOT_VERSION,
                "epoch": self.epoch,
                "ts": time.time(),
                "kv": {ns: dict(t) for ns, t in self._kv.items()
                       if ns in self._durable_ns},
                "functions": dict(self._functions),
                "named_actors": dict(self._named_actors),
                "actor_nodes": dict(self._actor_nodes),
                "small_objects": dict(self._small_objects),
                "lost_objects": set(self._lost_objects),
                # Dead nodes are dropped at snapshot time: their
                # node_dead cleanup already published, and an
                # ever-growing tombstone list defeats compaction.
                "nodes": [n.to_dict() for n in self._nodes.values()
                          if n.state != "dead"],
            }
            tmp = self._snap_path + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    pickle.dump(snap, f, protocol=5)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._snap_path)
            except OSError:
                # Snapshot failed (disk full is the likely way): back
                # off instead of re-dumping full state on every later
                # append, and don't leave the torn temp file behind.
                self._next_snapshot_try = time.monotonic() + 30.0
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return
            self._wal.close()
            self._wal = open(self._wal_path, "wb")
            self._wal_ops = 0
            self._last_snapshot_ts = time.time()
            # The fresh log still carries the epoch so a WAL-only
            # reader (snapshot deleted by an operator) stays correct.
            self._log("epoch", self.epoch)

    def status(self) -> dict:
        """Control-plane health card: epoch, uptime, WAL size,
        last-snapshot age, membership counts (`ray_tpu gcs` CLI)."""
        with self._lock:
            wal_bytes = 0
            if self._wal is not None:
                try:
                    wal_bytes = self._wal.tell()
                except (OSError, ValueError):
                    pass
            states: Dict[str, int] = {}
            stale = 0
            for n in self._nodes.values():
                states[n.state] = states.get(n.state, 0) + 1
                stale += 1 if n.stale and n.state != "dead" else 0
            now = time.time()
            return {
                "epoch": self.epoch,
                "uptime_s": now - self.started,
                "persistent": self._wal is not None,
                "wal_bytes": wal_bytes,
                "wal_ops_since_snapshot": self._wal_ops,
                "last_snapshot_age_s": (
                    None if self._last_snapshot_ts is None
                    else now - self._last_snapshot_ts),
                "recovered": self._recovered_ts is not None,
                "nodes": states,
                "stale_nodes": stale,
                "named_actors": len(self._named_actors),
                "actor_directory": len(self._actor_nodes),
                "objects_tracked": len(self._locations),
                "small_objects": len(self._small_objects),
                "op_counts": dict(self._op_counts),
            }

    # -- internal KV -------------------------------------------------------
    def kv_put(self, ns: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        with self._lock:
            self._count_op("kv_put")
            table = self._kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            if ns in self._durable_ns:
                self._log("kv_put", ns, key, value)
            waiters = self._kv_waiters.pop((ns, key), [])
        for cb in waiters:          # outside the lock: cbs do IO
            try:
                cb(value)
            except Exception:
                pass
        return True

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            self._count_op("kv_get")
            return self._kv.get(ns, {}).get(key)

    def kv_del(self, ns: str, key: bytes) -> bool:
        with self._lock:
            hit = self._kv.get(ns, {}).pop(key, None) is not None
            if hit and ns in self._durable_ns:
                self._log("kv_del", ns, key)
            return hit

    def kv_wait_register(self, ns: str, key: bytes,
                         cb: Callable[[bytes], None]
                         ) -> Optional[bytes]:
        """Return the value if present, else park `cb` for the next
        kv_put of this key."""
        with self._lock:
            v = self._kv.get(ns, {}).get(key)
            if v is not None:
                return v
            self._kv_waiters.setdefault((ns, key), []).append(cb)
            return None

    def kv_wait_unregister(self, ns: str, key: bytes, cb) -> None:
        with self._lock:
            lst = self._kv_waiters.get((ns, key))
            if lst is not None:
                try:
                    lst.remove(cb)
                except ValueError:
                    pass
                if not lst:
                    del self._kv_waiters[(ns, key)]

    def kv_keys(self, ns: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    # -- function table ----------------------------------------------------
    def register_function(self, function_id: bytes, blob: bytes) -> None:
        with self._lock:
            self._functions[function_id] = blob
            self._log("fn", function_id, blob)

    def fetch_function(self, function_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._functions.get(function_id)

    # -- named actors ------------------------------------------------------
    def register_named_actor(self, ns: str, name: str,
                             actor_id: bytes) -> bool:
        with self._lock:
            key = f"{ns}/{name}"
            if key in self._named_actors:
                return False
            self._named_actors[key] = actor_id
            self._log("actor_put", key, actor_id)
            return True

    def lookup_named_actor(self, ns: str, name: str) -> Optional[bytes]:
        with self._lock:
            return self._named_actors.get(f"{ns}/{name}")

    def drop_named_actor(self, actor_id: bytes) -> None:
        with self._lock:
            dead = [k for k, v in self._named_actors.items() if v == actor_id]
            for k in dead:
                del self._named_actors[k]
                self._log("actor_del", k)

    def list_named_actors(self, ns: Optional[str] = None) -> List[str]:
        with self._lock:
            if ns is None:
                return list(self._named_actors)
            return [k.split("/", 1)[1] for k in self._named_actors
                    if k.startswith(ns + "/")]

    # -- node membership & resources (gcs_node_manager.h:45) ---------------
    def register_node(self, node_id: bytes, host: str, control_port: int,
                      transfer_port: int,
                      resources_total: Dict[str, float]) -> None:
        with self._lock:
            self._count_op("register_node")
            info = NodeInfo(
                node_id, host, control_port, transfer_port, resources_total)
            self._nodes[node_id] = info
            self._log("node_reg", node_id, host, control_port,
                      transfer_port, dict(resources_total))
            snapshot = info.to_dict()
        # Publish the snapshot taken under the lock: re-reading
        # self._nodes[node_id] here raced a concurrent health-check
        # reap (KeyError on the conn thread) — an RT010 self-finding.
        self._publish_node("node_added", snapshot)

    def resync_node(self, node_id: bytes, host: str, control_port: int,
                    transfer_port: int,
                    resources_total: Dict[str, float],
                    objects: Iterable[Tuple[bytes, int]] = (),
                    inline: Iterable[Tuple[bytes, int, str, bytes]] = (),
                    actors: Iterable[bytes] = (),
                    draining: Optional[dict] = None) -> dict:
        """A node's bulk re-publication of its authoritative local
        state after a GCS restart or reconnect (reference: raylet
        resubscription rebuilding the restarted GCS).  Re-registers the
        node (clearing any stale tag), repopulates the soft object
        directory with its held copies, re-points the actor directory
        at its resident actors, and restores an in-progress drain.
        Idempotent — a node may resync on every reconnect.

        Returns {"epoch", "redrain": grace_s | None}: redrain is set
        when the GCS recovered a drain for this node that the node
        itself didn't report (GCS-initiated drain whose event was lost
        with the old process) — the server re-publishes node_draining
        so the node picks the drain back up."""
        redrain: Optional[float] = None
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or n.state == "dead":
                # Unknown (joined during the outage, or record already
                # reaped): a resync is as good as a registration.
                n = NodeInfo(node_id, host, control_port, transfer_port,
                             resources_total)
                self._nodes[node_id] = n
            else:
                n.host = host
                n.control_port = control_port
                n.transfer_port = transfer_port
                n.resources_total = dict(resources_total)
            n.stale = False
            n.last_heartbeat = time.time()
            self._log("node_reg", node_id, host, control_port,
                      transfer_port, dict(resources_total))
            if draining is not None:
                n.state = "draining"
                n.drain_deadline = float(draining.get("deadline")
                                         or time.time())
                n.drain_reason = draining.get("reason", "drain")
                self._log("node_drain", node_id, n.drain_deadline,
                          n.drain_reason)
            elif n.state == "draining":
                # Recovered drain the node doesn't know about (the
                # node_draining push died with the old GCS process).
                # Re-log it: the node_reg record above replays to a
                # fresh "alive" NodeInfo, so the drain must follow it
                # in the log or a second restart would forget it.
                redrain = max(0.0, (n.drain_deadline or time.time())
                              - time.time())
                self._log("node_drain", node_id,
                          n.drain_deadline or time.time(),
                          n.drain_reason)
            for aid in actors:
                self._actor_nodes[aid] = node_id
                self._log("actor_node", aid, node_id)
            info = n.to_dict()
        # Locations are soft state: re-add through the ordinary path so
        # parked location subscribers (readers that waited out the
        # outage) wake on the re-published copies.
        for oid, size in objects:
            self.add_location(oid, node_id, size, kind="shm")
        for oid, size, kind, data in inline:
            self.add_location(oid, None, size, kind=kind, data=data)
        self._publish_node("node_resynced", info)
        if redrain is not None:
            info = dict(info)
            info["reason"] = info.get("drain_reason") or "drain"
            info["grace_s"] = redrain
            self._publish_node("node_draining", info)
        return {"epoch": self.epoch,
                "redrain": redrain}

    def heartbeat(self, node_id: bytes,
                  resources_avail: Dict[str, float],
                  load: Optional[dict] = None) -> None:
        with self._lock:
            self._count_op("heartbeat")
            n = self._nodes.get(node_id)
            if n is None or n.state == "dead":
                return
            # Draining nodes keep heartbeating while they hand off
            # work; a heartbeat must NOT resurrect them to "alive" —
            # only last_heartbeat/resources update, the state machine
            # moves forward exclusively (alive -> draining -> dead).
            n.last_heartbeat = time.time()
            n.resources_avail = dict(resources_avail)
            if load is not None:
                n.load = dict(load)

    def drain_node(self, node_id: bytes, grace_s: float = 30.0,
                   reason: str = "drain requested") -> bool:
        """Begin a graceful departure: alive -> draining, published as
        a `node_draining` event (the node itself reacts by handing
        back queued work, migrating actors, and re-replicating sole
        object copies; peers stop targeting it).  Returns False for an
        unknown, already-draining, or dead node — the transition fires
        exactly once."""
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or n.state != "alive":
                return False
            n.state = "draining"
            n.drain_deadline = time.time() + max(grace_s, 0.0)
            n.drain_reason = reason
            self._log("node_drain", node_id, n.drain_deadline, reason)
            info = n.to_dict()
        info["reason"] = reason
        info["grace_s"] = max(grace_s, 0.0)
        self._publish_node("node_draining", info)
        return True

    def mark_node_dead(self, node_id: bytes, reason: str = "") -> None:
        lost_notifies = []
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or n.state == "dead":
                # Drain/death race guard: a drained node reports itself
                # dead AND the health check may fire on it — whichever
                # call runs second sees "dead" here and returns, so the
                # node_dead actor/object cleanup publishes exactly once.
                return
            n.state = "dead"
            self._log("node_dead", node_id)
            # Copies on a dead node are gone.  Subscribers waiting on an
            # object whose LAST copy just vanished must hear about it
            # (kind="lost") or they would block forever.
            for oid in list(self._locations):
                holders, size = self._locations[oid]
                holders.discard(node_id)
                if not holders and oid not in self._small_objects:
                    del self._locations[oid]
                    self._lost_objects.add(oid)
                    self._log("lost_add", oid)
                    subs = self._loc_subs.pop(oid, [])
                    if subs:
                        lost_notifies.append((oid, size, subs))
            dead_actors = [a for a, nid in self._actor_nodes.items()
                           if nid == node_id]
            for a in dead_actors:
                del self._actor_nodes[a]
                self._log("actor_node_del", a)
                self.drop_named_actor(a)
            info = n.to_dict()
        for oid, size, subs in lost_notifies:
            evt = {"object_id": oid, "node_id": None, "size": size,
                   "kind": "lost"}
            for cb in subs:
                try:
                    cb(oid, evt)
                except Exception:
                    pass
        info["reason"] = reason
        info["dead_actors"] = dead_actors
        self._publish_node("node_dead", info)

    def nodes(self, alive_only: bool = True) -> List[dict]:
        """alive_only means "not dead": draining nodes are still
        reachable and still serving (objects pull from them, their
        actors answer until migrated), so they stay in the cluster
        view — consumers that must not target them filter on
        state == "alive" (spill targets, placement, feasibility).
        Stale records (recovered, not yet re-synced) stay in the view
        too, tagged "stale": last-known is better than nothing while
        the cluster converges on a restarted GCS."""
        with self._lock:
            return [n.to_dict() for n in self._nodes.values()
                    if not alive_only or n.state != "dead"]

    def node_info(self, node_id: bytes) -> Optional[dict]:
        with self._lock:
            n = self._nodes.get(node_id)
            return n.to_dict() if n else None

    def check_health(self, timeout_s: float) -> List[dict]:
        """Mark nodes with stale heartbeats dead; returns newly-dead.

        Draining nodes get their drain-grace deadline instead of the
        plain heartbeat timeout: heartbeats naturally stop while a
        node finishes its drain sequence and exits, so silence alone
        is not death until the deadline has passed (a cleanly drained
        node reports itself dead before that).

        Stale records (recovered after a GCS restart, not yet
        re-synced) get gcs_resync_grace_s from the recovery instant:
        the silence the plain timeout would punish happened while the
        GCS itself was down."""
        now = time.time()
        resync_grace = max(config.gcs_resync_grace_s, timeout_s)
        with self._lock:
            stale = []
            for n in self._nodes.values():
                hb_stale = now - n.last_heartbeat > timeout_s
                if n.stale and n.state != "dead":
                    if now - n.last_heartbeat > resync_grace:
                        stale.append((n.node_id,
                                      "never re-synced after GCS "
                                      "restart"))
                    continue
                if n.state == "alive" and hb_stale:
                    stale.append((n.node_id, "missed heartbeats"))
                elif n.state == "draining" and hb_stale:
                    # Heartbeats continue THROUGH a drain (a clean exit
                    # reports itself dead), so silence during one means
                    # either the final exit race (give it the deadline)
                    # or a hard crash mid-drain — a long grace must not
                    # hide a dead node for minutes, so extended silence
                    # (3x the plain timeout) reaps it regardless.
                    if now > (n.drain_deadline or 0.0):
                        stale.append((n.node_id,
                                      "drain deadline exceeded "
                                      f"({n.drain_reason or 'drain'})"))
                    elif now - n.last_heartbeat > 3 * timeout_s:
                        stale.append((n.node_id,
                                      "crashed while draining "
                                      "(missed heartbeats)"))
        newly_dead = []
        for nid, reason in stale:
            self.mark_node_dead(nid, reason)
            newly_dead.append(self.node_info(nid))
        return newly_dead

    # -- object locations --------------------------------------------------
    def add_location(self, oid: bytes, node_id: Optional[bytes], size: int,
                     kind: str = "shm", data: Optional[bytes] = None
                     ) -> None:
        """Register a copy.  kind 'inline'/'error' payloads ride in the
        GCS record itself (small by construction) so readers skip the
        node-to-node pull."""
        with self._lock:
            self._count_op("add_location")
            holders, _ = self._locations.get(oid, (set(), 0))
            if node_id is not None:
                holders.add(node_id)
            self._locations[oid] = (holders, size)
            if oid in self._lost_objects:
                self._lost_objects.discard(oid)
                self._log("lost_del", oid)
            if kind in ("inline", "error") and data is not None:
                self._small_objects[oid] = (kind, data)
                self._log("small_obj", oid, kind, data)
            subs = list(self._loc_subs.get(oid, ()))
        evt = {"object_id": oid, "node_id": node_id, "size": size,
               "kind": kind}
        for cb in subs:
            try:
                cb(oid, evt)
            except Exception:
                pass

    def get_locations(self, oid: bytes) -> dict:
        with self._lock:
            self._count_op("get_locations")
            holders, size = self._locations.get(oid, (set(), 0))
            small = self._small_objects.get(oid)
            # Draining holders stay fetchable: their copies are valid
            # until the node actually exits (and the drain re-replicates
            # sole copies elsewhere before that).
            alive = [self._nodes[h].to_dict() for h in holders
                     if h in self._nodes
                     and self._nodes[h].state != "dead"]
            lost = oid in self._lost_objects
        out = {"nodes": alive, "size": size}
        if alive and all(n.get("stale") for n in alive):
            # Every holder is a recovered record not yet re-confirmed:
            # serve it (last-known beats nothing) but tagged, so pullers
            # know a fetch failure here means "wait for re-sync", not
            # "object lost".
            out["stale"] = True
        if small is not None:
            out["kind"], out["data"] = small
        else:
            out["kind"] = "shm" if alive else None
            if out["kind"] is None and lost:
                out["lost"] = True      # once READY; copies died
        return out

    def remove_object(self, oid: bytes) -> List[bytes]:
        """Owner-driven delete: drop the record; returns holder node ids
        (the server publishes object_deleted to them).  Subscribers
        still pulling hear kind='lost' so their pull loops terminate
        instead of polling a vanished record forever."""
        with self._lock:
            holders, size = self._locations.pop(oid, (set(), 0))
            if self._small_objects.pop(oid, None) is not None:
                self._log("small_obj_del", oid)
            if oid in self._lost_objects:
                self._lost_objects.discard(oid)
                self._log("lost_del", oid)
            subs = self._loc_subs.pop(oid, [])
        evt = {"object_id": oid, "node_id": None, "size": size,
               "kind": "lost"}
        for cb in subs:
            try:
                cb(oid, evt)
            except Exception:
                pass
        return list(holders)

    def remove_location(self, oid: bytes, node_id: bytes) -> None:
        """Drop one node from an object's holder set (replica freed or
        observed missing); the record itself stays."""
        with self._lock:
            entry = self._locations.get(oid)
            if entry is None:
                return
            entry[0].discard(node_id)

    def sub_location(self, oid: bytes,
                     cb: Callable[[bytes, dict], None]) -> None:
        fire = None
        with self._lock:
            if oid in self._locations or oid in self._small_objects:
                holders, size = self._locations.get(oid, (set(), 0))
                small = self._small_objects.get(oid)
                if small is not None:
                    fire = {"object_id": oid, "node_id": None,
                            "size": size, "kind": small[0]}
                elif holders:
                    fire = {"object_id": oid,
                            "node_id": next(iter(holders)),
                            "size": size, "kind": "shm"}
            self._loc_subs.setdefault(oid, []).append(cb)
        if fire is not None:
            cb(oid, fire)

    def unsub_location(self, oid: bytes, cb) -> None:
        with self._lock:
            subs = self._loc_subs.get(oid)
            if subs and cb in subs:
                subs.remove(cb)
                if not subs:
                    del self._loc_subs[oid]

    # -- actor directory ---------------------------------------------------
    def set_actor_node(self, actor_id: bytes, node_id: bytes) -> None:
        with self._lock:
            self._actor_nodes[actor_id] = node_id
            self._log("actor_node", actor_id, node_id)

    def get_actor_node(self, actor_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._actor_nodes.get(actor_id)

    def drop_actor(self, actor_id: bytes) -> None:
        with self._lock:
            if self._actor_nodes.pop(actor_id, None) is not None:
                self._log("actor_node_del", actor_id)
        self.drop_named_actor(actor_id)

    # -- node event pubsub -------------------------------------------------
    def sub_nodes(self, cb: Callable[[str, dict], None]) -> None:
        with self._lock:
            self._node_subs.append(cb)

    def unsub_nodes(self, cb) -> None:
        with self._lock:
            if cb in self._node_subs:
                self._node_subs.remove(cb)

    def _publish_node(self, event: str, info: dict) -> None:
        with self._lock:
            subs = list(self._node_subs)
        for cb in subs:
            try:
                cb(event, info)
            except Exception:
                pass
