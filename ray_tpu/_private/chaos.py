"""Deterministic fault injection (reference: src/ray/rpc/rpc_chaos.h +
RAY_testing_rpc_failure / RAY_testing_asio_delay_us, grown into a
first-class subsystem).

Every injection decision is drawn from ONE seeded RNG
(``config.chaos_seed``), so a failing schedule replays exactly: run the
same workload with the same seed and the same faults fire at the same
draw points.  The injected-fault trace (``trace()``) is the replay
witness — tests assert two runs with one seed produce identical traces.

Spec grammar (``config.chaos_spec`` / ``RAY_TPU_CHAOS_SPEC``)::

    spec  := entry ("," entry)*
    entry := site (":" key "=" value)*

``site`` is an rpc/message type (``submit_task``, ``get_objects``, ...),
a node-level hook (``dispatch``, ``serve.assign``, ``partition``), or
``*`` (every rpc site).  Keys:

    kind    error | drop | delay | kill_worker | evict | kill_replica
            | partition | preempt  (default: error)
    p       injection probability per eligible event (default 1.0)
    n       budget: total injections allowed; -1 = unlimited (default -1)
    interval_s
            storm spacing: minimum seconds between two firings of this
            spec (default 0 = no spacing).  With ``n`` this makes a
            whole failure storm ONE seeded, replayable entry — e.g.
            ``node:kind=preempt:n=3:interval_s=5`` is three
            preemptions at least 5s apart.  Rejected for the standing
            kinds (partition / gcs_partition), which have no discrete
            firings to space.
    lo_ms / hi_ms
            delay bounds for kind=delay (milliseconds)
    node    hex prefix of the target node id for kind=partition
    deadline_s
            kind=preempt: seconds between the simulated termination
            notice and the "VM" disappearing (0 = config.drain_grace_s)
    down_s  kind=kill_gcs: seconds the supervised GCS stays down before
            restart (default 1.0).  kind=gcs_partition: seconds the
            client<->GCS partition holds from first activation (0 =
            standing until clear())

Fault kinds and where they act:

* ``error``   — raise ``ConnectionLost`` before the rpc is sent.  The
  protocol layer retries injected/pre-send failures with backoff, so a
  budgeted error exercises the rpc retry path transparently.
* ``drop``    — a request/reply rpc raises pre-send (retried like
  ``error``); a one-way notify is silently dropped (lossy by design —
  recovery must come from a higher layer).
* ``delay``   — sleep uniform(lo_ms, hi_ms) before dispatch.
* ``kill_worker``  — at task dispatch (site ``dispatch``): SIGKILL the
  worker the task was just assigned to (exercises crash retry).
* ``evict``   — at ``get_objects``: evict a requested READY object's
  shm payload, forcing lineage reconstruction
  (``node_objects._try_reconstruct``).
* ``kill_replica`` — at ``serve.assign``: kill the replica the router
  just picked (exercises Serve failover).
* ``partition`` — standing condition: drop peer control AND
  object-transfer connections to nodes whose id matches ``node``.
* ``preempt`` — at the node monitor (site ``node``): deliver a
  simulated TPU-preemption notice with ``deadline_s`` of grace — the
  node begins a graceful drain; work that cannot finish or move by the
  deadline falls back to the ordinary kill-and-retry path.
* ``kill_gcs`` — at the cluster supervisor (site ``gcs``): SIGKILL the
  GCS process (or tear down an in-process server statefully-cold), then
  restart it from its WAL/snapshot after ``down_s`` — the kill-9
  control-plane drill (``cluster_utils.Cluster`` runs the supervisor).
* ``gcs_partition`` — standing condition at the GcsClient: drop
  client<->GCS traffic only (peer control + object transfer keep
  flowing), healing after ``down_s`` seconds — exercises the client
  reconnect/queueing path without killing the server.

The legacy env specs ``testing_rpc_failure`` ("method:N" → kind=error,
p=0.5, n=N) and ``testing_asio_delay_us`` ("method:lo:hi" microseconds)
are folded into the same schedule.

State is per-process.  The env/config spec reaches workers through the
inherited environment; the runtime API (``ray_tpu.util.chaos.inject`` /
``clear``) acts on the calling process — which, single-node, is where
the node service threads live, so driver-side ``inject()`` drives node
faults (dispatch kills, evictions) directly.

Unlike the old ``protocol._Chaos`` (parsed once, frozen, global unseeded
``random``), the schedule here is re-resolved when the config changes
(checked at most every 250 ms) and every mutation is lock-protected.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import config

FAULT_KINDS = ("error", "drop", "delay", "kill_worker", "evict",
               "kill_replica", "partition", "preempt", "kill_gcs",
               "gcs_partition")

# How often (at most) the env/config spec is re-read on the hot path.
_REFRESH_INTERVAL_S = 0.25


class FaultSpec:
    __slots__ = ("site", "kind", "p", "budget", "lo_ms", "hi_ms", "node",
                 "deadline_s", "down_s", "interval_s", "announced",
                 "activated_ts", "last_fired_ts")

    def __init__(self, site: str, kind: str = "error", p: float = 1.0,
                 n: int = -1, lo_ms: float = 0.0, hi_ms: float = 0.0,
                 node: str = "", deadline_s: float = 0.0,
                 down_s: float = 0.0, interval_s: float = 0.0) -> None:
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (valid: "
                f"{', '.join(FAULT_KINDS)})")
        if not site:
            raise ValueError("fault spec needs a site")
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"probability {p} not in [0, 1]")
        if hi_ms < lo_ms:
            raise ValueError(f"hi_ms {hi_ms} < lo_ms {lo_ms}")
        if kind == "partition" and not node:
            raise ValueError("kind=partition needs node=<hex prefix>")
        if deadline_s < 0.0:
            raise ValueError(f"deadline_s {deadline_s} < 0")
        if deadline_s and kind != "preempt":
            raise ValueError("deadline_s only applies to kind=preempt")
        if down_s < 0.0:
            raise ValueError(f"down_s {down_s} < 0")
        if down_s and kind not in ("kill_gcs", "gcs_partition"):
            raise ValueError(
                "down_s only applies to kind=kill_gcs/gcs_partition")
        if interval_s < 0.0:
            raise ValueError(f"interval_s {interval_s} < 0")
        if interval_s and kind in ("partition", "gcs_partition"):
            raise ValueError(
                "interval_s needs discrete firings; "
                f"kind={kind} is a standing condition")
        self.site = site
        self.kind = kind
        self.p = p
        self.budget = n
        self.lo_ms = lo_ms
        self.hi_ms = hi_ms
        self.node = node
        # kind=preempt: the simulated termination notice's deadline —
        # the drained node has this long before the "VM" is gone
        # (0.0 = use config.drain_grace_s).
        self.deadline_s = deadline_s
        # kind=kill_gcs: restart delay; kind=gcs_partition: partition
        # duration from first activation (0.0 = standing).
        self.down_s = down_s
        # Storm spacing: a firing is suppressed until interval_s has
        # passed since this spec's previous firing (n= gives the storm
        # its size, interval_s its cadence).
        self.interval_s = interval_s
        self.announced = False     # partition: trace once, not per check
        # gcs_partition: wall time the standing condition first matched
        # (its down_s window counts from here).
        self.activated_ts = 0.0
        self.last_fired_ts = 0.0   # monotonic ts of the last firing

    def _spaced_out(self, now: float) -> bool:
        """Storm spacing check: True while the spec must hold fire
        because interval_s has not elapsed since its last firing."""
        return (self.interval_s > 0.0 and self.last_fired_ts > 0.0
                and now - self.last_fired_ts < self.interval_s)

    def to_dict(self) -> Dict[str, Any]:
        out = {"site": self.site, "kind": self.kind, "p": self.p,
               "n": self.budget}
        if self.kind == "delay":
            out["lo_ms"], out["hi_ms"] = self.lo_ms, self.hi_ms
        if self.kind == "preempt":
            out["deadline_s"] = self.deadline_s
        if self.kind in ("kill_gcs", "gcs_partition"):
            out["down_s"] = self.down_s
        if self.interval_s:
            out["interval_s"] = self.interval_s
        if self.node:
            out["node"] = self.node
        return out


def parse_spec(spec: str) -> List[FaultSpec]:
    """Parse the chaos spec grammar; raises ValueError with the bad
    entry named."""
    out: List[FaultSpec] = []
    for raw in (spec or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        site = parts[0].strip()
        kwargs: Dict[str, Any] = {}
        for field in parts[1:]:
            key, sep, value = field.partition("=")
            if not sep:
                raise ValueError(
                    f"chaos spec entry {raw!r}: field {field!r} is not "
                    f"key=value")
            key = key.strip()
            value = value.strip()
            try:
                if key == "kind":
                    kwargs["kind"] = value
                elif key == "p":
                    kwargs["p"] = float(value)
                elif key == "n":
                    kwargs["n"] = int(value)
                elif key in ("lo_ms", "hi_ms", "deadline_s", "down_s",
                             "interval_s"):
                    kwargs[key] = float(value)
                elif key == "node":
                    kwargs["node"] = value
                else:
                    raise ValueError(f"unknown key {key!r}")
            except ValueError as e:
                raise ValueError(
                    f"chaos spec entry {raw!r}: {e}") from None
        try:
            out.append(FaultSpec(site, **kwargs))
        except (ValueError, TypeError) as e:
            raise ValueError(f"chaos spec entry {raw!r}: {e}") from None
    return out


def _legacy_specs() -> List[FaultSpec]:
    """testing_rpc_failure / testing_asio_delay_us compatibility."""
    out: List[FaultSpec] = []
    spec = config.testing_rpc_failure
    if spec:
        for part in spec.split(","):
            method, _, n = part.partition(":")
            # Old behavior: 50% coin flip per rpc while budget remains.
            out.append(FaultSpec(method.strip(), kind="error", p=0.5,
                                 n=int(n or 1)))
    dspec = config.testing_asio_delay_us
    if dspec:
        for part in dspec.split(","):
            method, lo, hi = part.split(":")
            out.append(FaultSpec(method.strip(), kind="delay",
                                 lo_ms=int(lo) / 1000.0,
                                 hi_ms=int(hi) / 1000.0))
    return out


class ChaosController:
    """Seeded, re-resolvable, thread-safe fault-injection schedule.

    ``seed``/``spec`` constructor overrides exist for unit tests; the
    process singleton (``chaos`` below) resolves both from config.
    """

    def __init__(self, seed: Optional[int] = None,
                 spec: Optional[str] = None) -> None:
        self._lock = threading.RLock()
        self._seed_override = seed
        self._spec_override = spec
        self._env_specs: List[FaultSpec] = []
        self._runtime_specs: List[FaultSpec] = []
        self._rng = random.Random(seed or 0)
        # Separate stream for retry-backoff jitter so backoff draws never
        # perturb the fault sequence (determinism of the fault trace).
        self._jitter_rng = random.Random((seed or 0) ^ 0x9E3779B9)
        self._trace: List[Tuple[int, str, str]] = []
        self._seq = 0
        self._fingerprint: Optional[tuple] = None
        self._next_check = 0.0
        self._enabled = False

    # -- schedule resolution -------------------------------------------
    def _refresh_locked(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now < self._next_check:
            return
        self._next_check = now + _REFRESH_INTERVAL_S
        try:
            fp = (self._seed_override
                  if self._seed_override is not None
                  else config.chaos_seed,
                  self._spec_override
                  if self._spec_override is not None
                  else config.chaos_spec,
                  config.testing_rpc_failure,
                  config.testing_asio_delay_us)
        except Exception:
            return
        if fp == self._fingerprint:
            return
        self._fingerprint = fp
        seed = int(fp[0] or 0)
        self._rng = random.Random(seed)
        self._jitter_rng = random.Random(seed ^ 0x9E3779B9)
        specs: List[FaultSpec] = []
        try:
            specs.extend(parse_spec(fp[1]))
        except ValueError:
            pass    # a bad env spec must not break every rpc
        if self._spec_override is None:
            try:
                specs.extend(_legacy_specs())
            except (ValueError, TypeError):
                pass    # same contract for the legacy grammar
        self._env_specs = specs
        self._enabled = bool(self._env_specs or self._runtime_specs)

    def refresh(self) -> None:
        """Force immediate re-resolution of the env/config schedule."""
        with self._lock:
            self._fingerprint = None
            self._refresh_locked(force=True)

    def _match(self, site: str) -> List[FaultSpec]:
        # Deliberately lock-free (hot path, every protocol message):
        # the spec lists are only rebound or appended to under the
        # lock — list reads under the GIL never crash on either, and
        # a one-message-stale schedule view is within contract.
        return [s for s in self._env_specs + self._runtime_specs  # ray-tpu: noqa[RT010]
                if s.site == site or s.site == "*"]

    # -- recording ------------------------------------------------------
    def _record_locked(self, site: str, kind: str) -> None:
        self._seq += 1
        self._trace.append((self._seq, site, kind))
        if len(self._trace) > 10_000:
            del self._trace[:5_000]
        _count_injection(kind)

    def trace(self) -> List[Tuple[int, str, str]]:
        """Injected-fault trace: [(seq, site, kind), ...] — the replay
        witness for seeded determinism tests."""
        with self._lock:
            return list(self._trace)

    def reset_trace(self) -> None:
        with self._lock:
            self._trace = []
            self._seq = 0

    # -- runtime API ----------------------------------------------------
    def inject(self, site: str, kind: str = "error", p: float = 1.0,
               n: int = -1, lo_ms: float = 0.0, hi_ms: float = 0.0,
               node: str = "", deadline_s: float = 0.0,
               down_s: float = 0.0, interval_s: float = 0.0) -> None:
        """Add a fault spec at runtime (this process)."""
        spec = FaultSpec(site, kind=kind, p=p, n=n, lo_ms=lo_ms,
                         hi_ms=hi_ms, node=node, deadline_s=deadline_s,
                         down_s=down_s, interval_s=interval_s)
        with self._lock:
            self._runtime_specs.append(spec)
            self._enabled = True

    def clear(self, site: Optional[str] = None) -> None:
        """Remove runtime-injected specs (all, or one site's)."""
        with self._lock:
            if site is None:
                self._runtime_specs = []
            else:
                self._runtime_specs = [s for s in self._runtime_specs
                                       if s.site != site]
            self._refresh_locked(force=True)
            self._enabled = bool(self._env_specs or self._runtime_specs)

    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            self._refresh_locked(force=True)
            return [s.to_dict()
                    for s in self._env_specs + self._runtime_specs]

    # -- injection points ----------------------------------------------
    def maybe_inject(self, site: str) -> Optional[str]:
        """Rpc-layer hook (protocol.Connection call/notify).  Returns
        "drop" when the message should be dropped, None otherwise;
        raises ConnectionLost for kind=error.  Delays sleep here."""
        if not self._enabled and time.monotonic() < self._next_check:
            return None
        delays: List[float] = []
        action: Optional[str] = None
        raise_error = False
        with self._lock:
            self._refresh_locked()
            if not self._enabled:
                return None
            for spec in self._match(site):
                if spec.kind in ("kill_worker", "evict", "kill_replica",
                                 "partition", "preempt", "kill_gcs",
                                 "gcs_partition"):
                    continue    # node-level kinds don't fire on rpcs
                if spec.budget == 0:
                    continue
                if spec._spaced_out(time.monotonic()):
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                if spec.budget > 0:
                    spec.budget -= 1
                spec.last_fired_ts = time.monotonic()
                self._record_locked(site, spec.kind)
                if spec.kind == "delay":
                    delays.append(self._rng.uniform(spec.lo_ms,
                                                    spec.hi_ms) / 1e3)
                elif spec.kind == "drop":
                    action = "drop"
                else:           # error
                    raise_error = True
        for d in delays:
            time.sleep(d)
        if raise_error:
            from ray_tpu._private.protocol import ConnectionLost
            raise ConnectionLost(
                f"chaos: injected failure for {site}")
        return action

    def armed(self, site: str, kind: str) -> bool:
        """Is any budgeted spec for (site, kind) armed?  Consumes no
        budget, draws no randomness, records nothing — the cheap
        pre-check before work whose eligibility must be verified
        before `fire()` spends the budget."""
        if not self._enabled and time.monotonic() < self._next_check:
            return False
        with self._lock:
            self._refresh_locked()
            return any(s.kind == kind and s.budget != 0
                       for s in self._match(site))

    def fire(self, site: str, kind: str) -> bool:
        """Node-level hook: should fault `kind` fire at `site` now?
        Consumes budget and records the injection when it does."""
        return self.fire_spec(site, kind) is not None

    def fire_spec(self, site: str, kind: str) -> Optional[Dict[str, Any]]:
        """Like fire(), but returns the firing spec's parameters (e.g.
        a preemption's deadline_s) instead of a bare bool; None when
        nothing fires.  Same budget/trace semantics as fire()."""
        if not self._enabled and time.monotonic() < self._next_check:
            return None
        with self._lock:
            self._refresh_locked()
            if not self._enabled:
                return None
            for spec in self._match(site):
                if spec.kind != kind or spec.budget == 0:
                    continue
                if spec._spaced_out(time.monotonic()):
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                if spec.budget > 0:
                    spec.budget -= 1
                spec.last_fired_ts = time.monotonic()
                self._record_locked(site, kind)
                return spec.to_dict()
        return None

    def partitioned(self, node_id: bytes) -> bool:
        """Standing node-partition check (peer control + transfer
        connections).  Does not consume budget; traced once per spec."""
        if not self._enabled and time.monotonic() < self._next_check:
            return False
        hexid = node_id.hex()
        with self._lock:
            self._refresh_locked()
            for spec in self._env_specs + self._runtime_specs:
                if spec.kind != "partition" or spec.budget == 0:
                    continue
                if hexid.startswith(spec.node):
                    if not spec.announced:
                        spec.announced = True
                        self._record_locked("partition", "partition")
                    return True
        return False

    def gcs_partitioned(self) -> bool:
        """Standing client<->GCS partition check (GcsClient call/notify
        + reconnect paths).  Does not consume budget; traced once per
        spec.  A spec with down_s > 0 heals that many seconds after its
        first activation (the window starts at the first check that
        matches, i.e. the first GCS op attempted under the partition),
        after which the spec disarms itself."""
        if not self._enabled and time.monotonic() < self._next_check:
            return False
        now = time.time()
        with self._lock:
            self._refresh_locked()
            for spec in self._env_specs + self._runtime_specs:
                if spec.kind != "gcs_partition" or spec.budget == 0:
                    continue
                if not spec.activated_ts:
                    spec.activated_ts = now
                if spec.down_s and now - spec.activated_ts >= spec.down_s:
                    spec.budget = 0     # healed: disarm for good
                    continue
                if not spec.announced:
                    spec.announced = True
                    self._record_locked("gcs", "gcs_partition")
                return True
        return False

    def jitter(self) -> float:
        """Uniform [0, 1) from the dedicated jitter stream — used by the
        node's retry backoff so delays replay under one seed without
        perturbing the fault draw sequence."""
        with self._lock:
            return self._jitter_rng.random()


def _count_injection(kind: str) -> None:
    """ray_tpu_chaos_injected_total{kind=...} — flushed to the node like
    any app metric.  Lazy import: metrics -> client -> protocol ->
    chaos would otherwise cycle at import time."""
    try:
        from ray_tpu.util.metrics import (CHAOS_INJECTED_METRIC,
                                          shared_counter)
        shared_counter(
            CHAOS_INJECTED_METRIC,
            description="chaos faults injected, by kind",
            tag_keys=("kind",)).inc(tags={"kind": kind})
    except Exception:
        pass


# Process singleton (the old protocol.chaos, promoted).
chaos = ChaosController()
