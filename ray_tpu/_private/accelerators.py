"""TPU accelerator management: chip detection, typed slice resources,
and per-worker chip visibility.

Reference surface: python/ray/_private/accelerators/tpu.py —
`TPUAcceleratorManager` detects chips via /dev/accel* device files and
GCE metadata (tpu.py:107-117), advertises the pod-slice gang resource
`TPU-{type}-head` on worker 0 (tpu.py:360-362), and pins workers to
their allocation by exporting `TPU_VISIBLE_CHIPS`.

This build keeps the same three capabilities but node-native: the node
service owns a chip-id pool sized by the node's TPU resource; each TPU
worker process leases chips at spawn and the pool is repaid when the
worker dies.  Detection never initializes a jax backend (merely-imported
jax is probed via xla_bridge state only) — touching the tunneled TPU
from the driver would serialize seconds of startup into `init()` and
deadlock when another process holds the tunnel.
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Dict, List, Optional


def detect_num_chips() -> int:
    """Chip count: env override, then device files, then an
    already-initialized jax backend."""
    env = os.environ.get("RAY_TPU_NUM_TPUS")
    if env is not None:
        return int(env)
    chips = len(glob.glob("/dev/accel*")) or len(glob.glob("/dev/vfio/*"))
    if chips:
        return chips
    import sys
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            from jax._src import xla_bridge as xb
            if xb.backends_are_initialized():
                return sum(1 for d in jax.devices()
                           if d.platform != "cpu")
        except Exception:
            pass
    return 0


def detect_accelerator_type() -> Optional[str]:
    """Slice type, e.g. "v5litepod-8" (reference: GCE instance metadata;
    here the standard TPU VM env vars)."""
    return (os.environ.get("TPU_ACCELERATOR_TYPE")
            or os.environ.get("RAY_TPU_ACCELERATOR_TYPE"))


def tpu_resources(num_chips: float) -> Dict[str, float]:
    """The resource dict a TPU host advertises: plain TPU chips, the
    typed per-chip resource, and — on slice worker 0 — the slice-head
    gang marker.  Fractional chip counts (a shared-chip node) still
    advertise the typed resources and the gang marker."""
    if not num_chips:
        return {}
    res: Dict[str, float] = {"TPU": float(num_chips)}
    acc_type = detect_accelerator_type()
    if acc_type:
        res[f"TPU-{acc_type}"] = float(num_chips)
        if os.environ.get("TPU_WORKER_ID", "0") == "0":
            res[f"TPU-{acc_type}-head"] = 1.0
    return res


class ChipAllocator:
    """Free-list of local chip ids; TPU workers lease
    `RAY_TPU_CHIPS_PER_WORKER` (default 1) chips at spawn."""

    def __init__(self, num_chips: int) -> None:
        self._free: List[int] = list(range(int(num_chips)))
        self._held: Dict[bytes, List[int]] = {}
        self._lock = threading.Lock()

    def acquire(self, worker_id: bytes,
                count: Optional[int] = None) -> List[int]:
        want = count if count is not None else int(
            os.environ.get("RAY_TPU_CHIPS_PER_WORKER", "1"))
        with self._lock:
            # Prefer a full-size lease; fall back to whatever is free.
            # A partial lease may undersize a multi-chip worker, but an
            # UNPINNED worker would initialize every chip on the node —
            # colliding with live exclusive leases (libtpu device
            # locks).  Only a fully-drained pool spawns unpinned, and
            # then node resource accounting (TPU: n) is what bounds how
            # many TPU tasks actually run concurrently.
            take = self._free[:want]
            self._free = self._free[want:]
            if take:
                self._held[worker_id] = take
            return take

    def release(self, worker_id: bytes) -> None:
        with self._lock:
            chips = self._held.pop(worker_id, None)
            if chips:
                # Repay in sorted order so reuse is deterministic.
                self._free = sorted(self._free + chips)

    def visible_env(self, chips: List[int]) -> Dict[str, str]:
        """Env pinning a worker to its lease (reference:
        tpu.py set_current_process_visible_accelerator_ids)."""
        if not chips:
            return {}
        ids = ",".join(str(c) for c in chips)
        return {"TPU_VISIBLE_CHIPS": ids}
