"""Cross-process trace-context propagation for task-lifecycle tracing.

Reference analog: python/ray/util/tracing/tracing_helper.py — the
reference injects OpenTelemetry contexts into task specs so a Serve
request renders as one trace across proxy/router/replica/worker
processes.  Here the context is a plain dict {trace_id, span_id} held
in a contextvar:

* the submitting side stamps the outgoing task spec with
  ``trace_ctx = {trace_id, parent_span_id}`` (client.submit_task);
* the executing worker activates a child context around the task body
  (worker_main), so spans opened inside the task — and any tasks IT
  submits — chain to the same trace;
* span ids are deterministic where two processes must agree without a
  handshake: the per-task *lifecycle* span id is derived from the task
  id, so the node service (which emits the lifecycle record) and the
  worker (which parents its execute span under it) independently
  compute the same id.

Ids follow the W3C/OTLP sizes: trace_id = 16 bytes (32 hex chars),
span_id = 8 bytes (16 hex chars).
"""

from __future__ import annotations

import contextvars
import os
from typing import Dict, Optional

_trace_ctx: "contextvars.ContextVar[Optional[Dict[str, str]]]" = \
    contextvars.ContextVar("rtpu_trace_ctx", default=None)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def lifecycle_span_id(task_id: bytes) -> str:
    """The task's lifecycle span id — deterministic so the node service
    and the executing worker agree on it without coordination."""
    return task_id[:8].hex()


def task_trace_id(spec: dict) -> str:
    """Trace id for a task with no inherited context: derived from the
    task id so every process computes the same root."""
    tc = spec.get("trace_ctx") or {}
    return tc.get("trace_id") or spec["task_id"].hex()


def current() -> Optional[Dict[str, str]]:
    """The active {trace_id, span_id} context, or None."""
    return _trace_ctx.get()


def set_current(ctx: Optional[Dict[str, str]]):
    return _trace_ctx.set(ctx)


def reset(token) -> None:
    _trace_ctx.reset(token)


def for_submit() -> Optional[Dict[str, str]]:
    """Wire form attached to an outgoing task spec: the submitter's
    span becomes the parent of the task's lifecycle span."""
    ctx = _trace_ctx.get()
    if ctx is None:
        return None
    return {"trace_id": ctx["trace_id"],
            "parent_span_id": ctx["span_id"]}


def child_span() -> Dict[str, str]:
    """A new span inheriting the ambient trace (or rooting a new one)."""
    ctx = _trace_ctx.get()
    if ctx is None:
        return {"trace_id": new_trace_id(), "span_id": new_span_id(),
                "parent_span_id": None}
    return {"trace_id": ctx["trace_id"], "span_id": new_span_id(),
            "parent_span_id": ctx["span_id"]}


def activate_for_task(spec: dict):
    """Worker-side: activate the execute-span context for a task body.

    Stores the resolved ids on the spec (``spec["_trace"]``) so the
    completion report can stamp the profile event even after the
    contextvar is reset (async actor paths report from a callback).
    Returns the contextvar token for reset().
    """
    info = {"trace_id": task_trace_id(spec),
            "span_id": new_span_id(),
            "parent_span_id": lifecycle_span_id(spec["task_id"])}
    spec["_trace"] = info
    return _trace_ctx.set(info)


# ---------------------------------------------------------------------------
# lifecycle stage arithmetic (shared by node metrics, summarize_tasks,
# and the chrome-trace expansion in util/profiling.timeline)
# ---------------------------------------------------------------------------

# (stage label, start checkpoint, end checkpoint).  Checkpoints are the
# transition timestamps the node service records on each TaskRecord:
# submitted -> queued -> [deps_fetched] -> worker_assigned ->
# executing -> finished.
STAGE_SPANS = (
    ("submit", "submitted", "queued"),
    ("queued", "queued", "worker_assigned"),
    ("dispatch", "worker_assigned", "executing"),
    ("executing", "executing", "finished"),
)

STAGE_DURATION_PAIRS = STAGE_SPANS + (
    ("deps_fetch", "queued", "deps_fetched"),
    # Time spent pulling remote dependencies (pull_wait is stamped when
    # the node arms cross-node pulls for a task's deps) — the transfer
    # plane's share of deps_fetch.
    ("pull_wait", "pull_wait", "deps_fetched"),
    ("total", "submitted", "finished"),
)


def stage_durations(stages: Dict[str, float]) -> Dict[str, float]:
    """Per-stage wall-clock durations from a checkpoint dict; stages
    whose checkpoints were never recorded are omitted."""
    out: Dict[str, float] = {}
    for label, a, b in STAGE_DURATION_PAIRS:
        if a in stages and b in stages and stages[b] >= stages[a]:
            out[label] = stages[b] - stages[a]
    return out


def stage_intervals(stages: Dict[str, float]):
    """Contiguous (label, start, end) intervals for timeline export."""
    out = []
    for label, a, b in STAGE_SPANS:
        if a in stages and b in stages and stages[b] >= stages[a]:
            out.append((label, stages[a], stages[b]))
    return out
