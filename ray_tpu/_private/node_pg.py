"""Placement groups: creation/2PC reserve-commit/bundle accounting.

Mixin split out of node_service.py (reference:
python/ray/util/placement_group.py:41 API, 2PC at
src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h:283).  Shares
NodeService's state and lock; see node_objects.py for the split
rationale.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import serialization as ser
from ray_tpu import exceptions as exc
from ray_tpu._private.node_state import (
    Bundle, FAILED, ObjectEntry, PENDING, TaskRecord, _ConnCtx,
    _place_bundles)


class PlacementGroupMixin:
    # ------------------------------------------------------------------
    # placement groups (reference: python/ray/util/placement_group.py:41,
    # 2PC at src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h:283)
    # ------------------------------------------------------------------
    def _h_create_pg(self, ctx: _ConnCtx, m: dict) -> None:
        pg_id = m["pg_id"]
        with self.lock:
            rec = {"bundles": m["bundles"], "strategy": m["strategy"],
                   "name": m.get("name"), "ready_oid": m["ready_oid"],
                   "state": "pending", "nodes": None}
            self.pgs[pg_id] = rec
            e = self.objects.setdefault(m["ready_oid"], ObjectEntry())
            e.refcount = max(e.refcount, 1)
        threading.Thread(target=self._pg_create_loop, args=(pg_id,),
                         daemon=True, name="rtpu-pg-create").start()
        ctx.reply(m, {"ok": True})

    def _h_remove_pg(self, ctx: _ConnCtx, m: dict) -> None:
        pg_id = m["pg_id"]
        with self.lock:
            rec = self.pgs.get(pg_id)
            if rec is None:
                ctx.reply(m, {"ok": False})
                return
            was_pending = rec["state"] == "pending"
            rec["state"] = "removed"
            if was_pending:
                # Resolve pg.ready() waiters instead of hanging them.
                blob = ser.dumps(ValueError(
                    "placement group was removed before it was placed"))
                self._register_object(rec["ready_oid"], "error", blob,
                                      len(blob), state=FAILED)
            nodes = rec["nodes"] or []
            self._schedule()
        self._release_bundles(pg_id, list(enumerate(nodes)))
        ctx.reply(m, {"ok": True})

    def _h_pg_state(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            rec = self.pgs.get(m["pg_id"])
            ctx.reply(m, {"state": rec["state"] if rec else "unknown",
                          "nodes": rec["nodes"] if rec else None})

    def _h_reserve_bundle(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            ok = self._reserve_bundle_local(
                m["pg_id"], m["bundle_index"], m["resources"])
        ctx.reply(m, {"ok": ok})

    def _h_return_bundle(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            self._return_bundle_local(m["pg_id"], m["bundle_index"])
            self._schedule()

    def _h_revoke_bundle(self, ctx: _ConnCtx, m: dict) -> None:
        self._revoke_bundle_local(m["pg_id"], m["bundle_index"])

    def _revoke_bundle_local(self, pg_id: bytes, idx: int) -> None:
        """Return a bundle AND kill the actors created in it (the
        re-placement path: the gang is moving, so members left on
        surviving nodes must die — reference: GCS destroys actors on
        rescheduled bundles)."""
        with self.lock:
            victims = [
                a for a in self.actors.values()
                if a.state != "dead"
                and (a.spec.get("pg") or {}).get("id") == pg_id
                and (a.spec.get("pg") or {}).get("bundle") == idx]
            for a in victims:
                a.restarts_left = 0
                self._mark_actor_dead(
                    a, "placement group bundle revoked (gang "
                       "re-placed after a member node died)")
            self._return_bundle_local(pg_id, idx)
            self._schedule()

    def _release_bundles(self, pg_id: bytes,
                         entries: List[Tuple[int, bytes]],
                         revoke: bool = False) -> None:
        """Release bundles across nodes: local ones directly, remote
        ones via return_bundle/revoke_bundle notifies (best-effort —
        an unreachable node's bundles die with it).  Never called
        under self.lock."""
        msg_type = "revoke_bundle" if revoke else "return_bundle"
        for idx, nid in entries:
            if nid == self.node_id:
                if revoke:
                    self._revoke_bundle_local(pg_id, idx)
                else:
                    with self.lock:
                        self._return_bundle_local(pg_id, idx)
                        self._schedule()
                continue
            ninfo = self._node_info(nid)
            if ninfo is None:
                continue
            try:
                self._peer_conn_to(ninfo).notify(
                    {"type": msg_type, "pg_id": pg_id,
                     "bundle_index": idx})
            except Exception:
                pass

    def _reserve_bundle_local(self, pg_id: bytes, idx: int,
                              res: Dict[str, float]) -> bool:
        """Phase-1 reserve: carve the bundle out of this node's available
        pool.  Caller holds self.lock."""
        key = (pg_id, idx)
        if key in self.bundles:
            return True     # idempotent (2PC retry)
        if not self._take(res):
            return False
        self.bundles[key] = Bundle(res)
        return True

    def _return_bundle_local(self, pg_id: bytes, idx: int) -> None:
        """Release a bundle back to the node pool.  Running tasks keep
        their share until completion (their give-back routes to the node
        pool once the bundle is gone).  Caller holds self.lock."""
        b = self.bundles.pop((pg_id, idx), None)
        if b is not None:
            self._give_back(b.free)

    def _pg_create_loop(self, pg_id: bytes) -> None:
        """Coordinator: place bundles, 2PC reserve/commit, retrying while
        resources are transiently busy; fails the ready object if no
        placement can ever exist."""
        while not self._shutdown:
            with self.lock:
                rec = self.pgs.get(pg_id)
                if rec is None or rec["state"] != "pending":
                    return
                bundles = rec["bundles"]
                strategy = rec["strategy"]
                my_avail = dict(self.resources_avail)
                my_total = dict(self.resources_total)
            view = [{"node_id": self.node_id, "self": True,
                     "resources_avail": my_avail,
                     "resources_total": my_total, "state": "alive"}]
            if self.multinode:
                view += [n for n in self._cluster_view
                         if n.get("state") == "alive"
                         and n["node_id"] != self.node_id]
            assignment = _place_bundles(bundles, strategy, view,
                                        use_avail=True)
            if assignment is None:
                if _place_bundles(bundles, strategy, view,
                                  use_avail=False) is None:
                    # No placement even against TOTALS.  With a live
                    # autoscaler lease the gang stays PENDING as
                    # demand (the heartbeat carries it; the autoscaler
                    # bin-packs whole node sets for it) — otherwise
                    # fail fast (reference: infeasible PG handling vs
                    # autoscaler demand).
                    if self._autoscaler_live():
                        time.sleep(0.2)
                        continue
                    blob = ser.dumps(exc.InfeasibleResourceError(
                        f"placement group {pg_id.hex()[:8]} "
                        f"({strategy}, {bundles}) cannot fit on any "
                        f"node combination"))
                    with self.lock:
                        rec["state"] = "failed"
                        self._register_object(rec["ready_oid"], "error",
                                              blob, len(blob),
                                              state=FAILED)
                    return
                time.sleep(0.1)
                continue
            if self._pg_try_commit(pg_id, rec, bundles, assignment):
                return
            time.sleep(0.1)

    def _pg_try_commit(self, pg_id: bytes, rec: dict, bundles: List[dict],
                       assignment: List[dict]) -> bool:
        """2PC: reserve every bundle on its assigned node; roll back all
        on any failure."""
        reserved: List[Tuple[int, dict]] = []
        ok = True
        for idx, target in enumerate(assignment):
            if target.get("self"):
                with self.lock:
                    got = self._reserve_bundle_local(pg_id, idx,
                                                     bundles[idx])
            else:
                try:
                    got = self._peer_conn_to(target).call(
                        {"type": "reserve_bundle", "pg_id": pg_id,
                         "bundle_index": idx,
                         "resources": bundles[idx]},
                        timeout=10.0)["ok"]
                except Exception:
                    got = False
            if not got:
                ok = False
                break
            reserved.append((idx, target))
        if not ok:
            self._release_bundles(
                pg_id, [(i, t["node_id"]) for i, t in reserved])
            return False
        blob = ser.dumps(True)
        rollback: List[Tuple[int, dict]] = []
        with self.lock:
            if rec["state"] != "pending":
                # remove_placement_group raced the commit: undo the
                # reserves instead of resurrecting a removed PG.
                rollback = reserved
            else:
                rec["nodes"] = [t["node_id"] for t in assignment]
                rec["state"] = "created"
                self._register_object(rec["ready_oid"], "inline", blob,
                                      len(blob))
                self._schedule()
        self._release_bundles(
            pg_id, [(i, t["node_id"]) for i, t in rollback])
        return True

    def _create_actor_with_pg(self, ctx: _ConnCtx, m: dict) -> None:
        """Wait for the actor's placement group to commit, then create
        the actor locally or forward the whole creation to the bundle's
        node (side thread; replies to the original create_actor call)."""
        spec = m["spec"]
        pg = spec["pg"]
        deadline = time.time() + 120.0
        target: Optional[bytes] = None
        while time.time() < deadline and not self._shutdown:
            with self.lock:
                rec = self.pgs.get(pg["id"])
                state = rec["state"] if rec else "unknown"
                target = self._pg_bundle_node(pg) if rec else None
            if state == "created":
                break
            if state in ("failed", "removed", "unknown"):
                ctx.reply(m, {"__error__": ValueError(
                    f"placement group is {state}")})
                return
            time.sleep(0.05)
        else:
            ctx.reply(m, {"__error__": TimeoutError(
                "placement group did not become ready within 120s")})
            return
        if target is None or target == self.node_id or not self.multinode:
            # Bundle is local (or single-node): run the normal creation
            # path — the bundle check at the top will now pass.
            self._h_create_actor(ctx, m)
            return
        ninfo = self._node_info(target)
        if ninfo is None:
            ctx.reply(m, {"__error__": RuntimeError(
                "placement group bundle's node is gone")})
            return
        actor_id = spec["actor_id"]
        self._actor_homes[actor_id] = target
        spec2 = dict(spec)
        spec2["creation_task"] = dict(spec2["creation_task"])
        spec2["creation_task"]["owner_node"] = self.node_id
        crec = TaskRecord(spec2["creation_task"])
        with self.lock:
            self.forwarded[crec.task_id] = (crec, target)
        try:
            conn = self._peer_conn_to(ninfo)
            conn.call({"type": "create_actor", "spec": spec2},
                      timeout=30.0)
            ctx.reply(m, {"ok": True})
        except Exception as e:
            self._actor_homes.pop(actor_id, None)
            with self.lock:
                self.forwarded.pop(crec.task_id, None)
            ctx.reply(m, {"__error__": e})

    def _pg_on_node_dead(self, nid: bytes) -> None:
        """Re-place committed placement groups that had bundles on a
        dead node (reference: gcs_placement_group_manager.cc
        OnNodeDead -> reschedule path).  Gang semantics: release every
        SURVIVING bundle and redo the whole 2PC placement — a partial
        gang is useless to its SPMD consumers (a TPU slice with a dead
        host has no ICI ring), and the autoscaler/slice-reconciler will
        produce replacement nodes the retry loop then lands on."""
        to_repair: List[Tuple[bytes, List[Tuple[int, bytes]]]] = []
        with self.lock:
            for pg_id, rec in self.pgs.items():
                if rec["state"] != "created" or not rec["nodes"] \
                        or nid not in rec["nodes"]:
                    continue
                nodes = rec["nodes"]
                rec["state"] = "pending"
                rec["nodes"] = None
                to_repair.append((pg_id, [
                    (i, n) for i, n in enumerate(nodes) if n != nid]))
        for pg_id, survivors in to_repair:
            # Revoke (return + kill actors): gang members stranded on
            # surviving nodes must not outlive the re-placement.
            self._release_bundles(pg_id, survivors, revoke=True)
            threading.Thread(target=self._pg_create_loop,
                             args=(pg_id,), daemon=True,
                             name="rtpu-pg-repair").start()

    def _pg_bundle_node(self, pg: dict) -> Optional[bytes]:
        """Home node of a pg bundle, from the coordinator record.  Caller
        holds self.lock; returns None while the PG is still pending."""
        rec = self.pgs.get(pg["id"])
        if rec is None or rec["nodes"] is None:
            return None
        try:
            return rec["nodes"][pg["bundle"]]
        except IndexError:
            return None
