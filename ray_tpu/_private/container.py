"""Container-image isolation for workers (runtime_env image_uri).

Reference analog: the container/image_uri runtime-env plugin
(python/ray/_private/runtime_env/image_uri.py, applied by the per-node
agent at _private/runtime_env/agent/runtime_env_agent.py:161): the
worker process for a task/actor whose runtime_env names an image runs
INSIDE that image, giving multi-tenant clusters dependency isolation
without pip/conda (this repo rejects in-cluster installs by design —
image isolation is the sanctioned alternative).

The node service spawns such workers through ``build_worker_argv``:
the normal worker command wrapped in ``<runtime> run`` with the
session/state paths bind-mounted and the worker's control env passed
explicitly.  The runtime binary is a seam — ``podman`` by default
(rootless-friendly), ``RAY_TPU_CONTAINER_RUNTIME`` overrides, and CI
points it at a fake that records the image and execs the command,
which exercises every layer except the kernel namespace itself.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Sequence


def runtime_binary() -> str:
    return os.environ.get("RAY_TPU_CONTAINER_RUNTIME", "podman")


# Env vars the worker needs to find its node service + store + session,
# plus its accelerator lease (TPU_VISIBLE_CHIPS pins concurrent TPU
# workers to disjoint chips — dropping it would let two containerized
# workers grab the same device); everything else inside the container
# comes from the image.
_PASS_KEYS = ("RAY_TPU_WORKER_ID", "RAY_TPU_NODE_SOCKET",
              "RAY_TPU_STORE_PATH", "RAY_TPU_SESSION_DIR",
              "PYTHONPATH", "JAX_PLATFORMS", "TPU_VISIBLE_CHIPS",
              "PALLAS_AXON_POOL_IPS")


def build_worker_argv(image: str, env: Dict[str, str],
                      mounts: Sequence[str],
                      python: Optional[str] = None) -> List[str]:
    """argv that runs ``python -m ray_tpu._private.worker_main`` inside
    `image`.

    --network/--ipc/--pid host: the worker speaks a unix socket to the
    node service and maps the host's /dev/shm store segment — the
    container isolates the FILESYSTEM (dependencies), not the runtime's
    data plane (same trade the reference's container plugin makes:
    image_uri.py passes the session socket dir through).
    """
    argv = [runtime_binary(), "run", "--rm",
            "--network=host", "--ipc=host", "--pid=host"]
    seen = set()
    for m in list(mounts) + ["/dev/shm"]:
        m = os.path.abspath(m)
        if m and m not in seen and os.path.exists(m):
            seen.add(m)
            argv += ["-v", f"{m}:{m}"]
    for k in _PASS_KEYS:
        if k in env:
            argv += ["--env", f"{k}={env[k]}"]
    argv += [image, python or "python3", "-m",
             "ray_tpu._private.worker_main"]
    return argv


def image_of(runtime_env: Optional[dict]) -> Optional[str]:
    """The image a task/actor's runtime env pins, if any."""
    if not runtime_env:
        return None
    return runtime_env.get("image_uri") or None
