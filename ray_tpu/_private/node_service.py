"""Per-node control plane: scheduler, worker pool, object directory.

This is the analog of the reference's raylet (src/ray/raylet/node_manager.h:119
NodeManager + worker_pool.h:174 WorkerPool + scheduling/cluster_task_manager.h:42)
fused with the single-node portion of the GCS.  Differences by design:

* One coarse-grained state lock + thread-per-connection instead of an asio
  event loop — connection counts on a node are small (tens of workers).
* The object *data* plane never touches this service: payloads live in the
  native shm store (shared mmap) or inline in messages; the service holds
  only the directory (who's ready, where, refcounts) the way the
  reference's ownership tables do (core_worker/reference_count.h:64).
* Dependency tracking happens here (tasks are dispatched only when their
  top-level ObjectRef args are ready), mirroring the reference's
  raylet-side DependencyManager rather than blocking workers.
"""

from __future__ import annotations

import os
import queue
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import serialization as ser
from ray_tpu._private.chaos import chaos
from ray_tpu._private.config import config
from ray_tpu._private.gcs import GlobalControlState
from ray_tpu._private.node_agent import NodeAgentMixin
from ray_tpu._private.node_drain import DrainMixin
from ray_tpu._private.node_native import NativeWorkerMixin
from ray_tpu._private.node_objects import ObjectPlaneMixin
from ray_tpu._private.node_pg import PlacementGroupMixin
from ray_tpu._private.node_streams import StreamChannelMixin
from ray_tpu._private.protocol import ConnectionLost, recv_msg, send_msg
from ray_tpu.devtools import leaksan
from ray_tpu import exceptions as exc
from ray_tpu._private.node_state import (  # noqa: F401
    ActorRecord, Bundle, FAILED, ObjectEntry, PENDING, READY,
    TaskRecord, WorkerHandle, _ConnCtx, _OID, _charge, _fits,
    _place_bundles, _reference_kind, _uncharge, _unregister_waiter)


def _rpc_args_summary(msg: dict, max_len: int = 512) -> str:
    """Bounded one-line summary of an RPC message's fields for the
    slow-RPC capture: scalar values truncated, bulk payloads reduced
    to type + size (a capture must never serialize object bytes)."""
    parts = []
    for k, v in list(msg.items())[:12]:
        if k == "__req_id__":
            continue
        if isinstance(v, (bytes, bytearray)):
            parts.append(f"{k}=<{len(v)}B>")
        elif isinstance(v, (str, int, float, bool)) or v is None:
            parts.append(f"{k}={str(v)[:48]}")
        else:
            try:
                size = len(v)  # type: ignore[arg-type]
            except TypeError:
                size = -1
            parts.append(f"{k}=<{type(v).__name__}"
                         + (f" len={size}" if size >= 0 else "") + ">")
    return " ".join(parts)[:max_len]


class NodeService(ObjectPlaneMixin, PlacementGroupMixin,
                  StreamChannelMixin, NodeAgentMixin,
                  NativeWorkerMixin, DrainMixin):
    """Per-node daemon: scheduler, worker pool, object directory.

    Single-node: runs inside the driver process (threads) with an
    embedded GlobalControlState.  Multi-node (gcs_address given): the
    same object connects to a GCS process (gcs_service.GcsClient), opens
    TCP control + object-transfer listeners for its peers, heartbeats
    resources, and spills work over / pulls objects across nodes — the
    raylet role (reference: node_manager.h:119 + object_manager.h:117 +
    cluster_task_manager.h:42 spillback)."""

    def __init__(self, session_dir: str, resources: Dict[str, float],
                 store_path: str, store_capacity: int,
                 gcs: Optional[GlobalControlState] = None,
                 gcs_address: Optional[Tuple[str, int]] = None,
                 node_id: Optional[bytes] = None) -> None:
        self.session_dir = session_dir
        self.socket_path = os.path.join(session_dir, "node.sock")
        self.store_path = store_path
        self.store_capacity = store_capacity
        self.node_id = node_id or os.urandom(16)
        self.gcs_address = gcs_address
        self.multinode = gcs_address is not None
        # GCS pushes + node events are handled on a dedicated thread: the
        # GcsClient receiver thread must never block on self.lock, or a
        # blocking gcs.call() made while holding the lock would deadlock
        # (the reply is parked behind the stuck push).
        self._gcs_events: "queue.Queue" = queue.Queue()
        if self.multinode:
            from ray_tpu._private.gcs_service import GcsClient
            self.gcs = GcsClient(gcs_address[0], gcs_address[1],
                                 push_handler=lambda m:
                                 self._gcs_events.put(("push", m)),
                                 on_reconnect=lambda epoch:
                                 self._gcs_events.put(("resync", epoch)))
        else:
            self.gcs = gcs or GlobalControlState()
        # Last GCS recovery epoch this node confirmed (via registration
        # or resync); a bump means the control plane restarted and this
        # node re-published its state (ray_tpu_gcs_restarts_total).
        self._gcs_epoch: Optional[int] = None
        # Periodic gcs_status poll (wal size gauge, `ray_tpu gcs`).
        self._gcs_status: dict = {}
        self._next_gcs_status = 0.0
        # node_id -> Connection to that node's control listener
        self._peer_conns: Dict[bytes, Any] = {}
        self._peer_lock = threading.Lock()
        # task_id -> (TaskRecord, target node_id) for spilled-over tasks
        self.forwarded: Dict[bytes, Tuple[TaskRecord, bytes]] = {}
        # per-peer FIFO forward queues: one sender thread per target so
        # two calls to the same remote actor can never reorder in flight
        self._fwd_queues: Dict[bytes, "queue.Queue"] = {}
        # cluster resource view (from GCS), refreshed with each heartbeat
        self._cluster_view: List[dict] = []
        # actor_id -> node_id hint for actors living on other nodes
        self._actor_homes: Dict[bytes, bytes] = {}
        # actor_id -> death reason, for remote actors whose node died
        self._remote_actor_tombstones: Dict[bytes, str] = {}
        # object ids with an in-flight pull (owned by the pull pool)
        self._pulls_inflight: set = set()
        # pulls whose local entry was deleted mid-flight: the loop must
        # exit instead of polling a vanished GCS record forever
        self._cancelled_pulls: set = set()
        # Bounded pull-manager pool (reference: pull_manager.h request
        # pipelining; replaces thread-per-object pulls).  A heap of
        # (due, seq, oid) attempts consumed by at most
        # config.object_pull_workers threads; an attempt that can't
        # finish requeues itself with a short delay instead of camping
        # on a pool slot.
        self._pull_cond = threading.Condition()
        self._pull_heap: List[Tuple[float, int, bytes]] = []
        self._pull_due: Dict[bytes, float] = {}
        self._pull_running: set = set()
        self._pull_seq = 0
        self._pull_idle = 0
        # per-pull subscription state: oid -> {"cb", "subscribed",
        # "last_event"}
        self._pull_state: Dict[bytes, dict] = {}
        # Location cache fed by pull-time GCS lookups: oid ->
        # (frozenset(holder node ids), size).  Drives locality-aware
        # spillback scoring without a GCS round-trip under the lock.
        self._obj_loc_cache: Dict[bytes, Tuple[frozenset, int]] = {}
        # (oid, node_id) -> consecutive mid-transfer failures; two
        # strikes prune the holder from the GCS directory.
        self._holder_strikes: Dict[Tuple[bytes, bytes], int] = {}
        # Cached read fds for spilled objects served to peers
        # (os.pread instead of open+seek per chunk).
        self._spill_fds: Dict[bytes, Tuple[int, str]] = {}
        self._spill_fd_lock = threading.Lock()
        # Oids whose spill fd was dropped because the object left the
        # directory (deleted / spill file destroyed): a late chunk
        # request racing the delete — e.g. a fetch aborted by a
        # partition whose last request lands after the owner's global
        # delete — must serve its bytes WITHOUT re-caching the fd; the
        # delete already ran, so nothing would ever close a re-cached
        # entry (leak-ledger self-finding).  Cleared when the oid is
        # re-spilled.  Guarded by _spill_fd_lock; bounded.
        self._spill_dead: set = set()
        # (pg_id, bundle_index) -> Bundle reserved ON THIS NODE
        self.bundles: Dict[Tuple[bytes, int], Bundle] = {}
        # pg_id -> coordinator record for PGs created via this node:
        # {"bundles", "strategy", "name", "ready_oid",
        #  "state": pending|created|failed|removed,
        #  "nodes": [node_id per bundle]}
        self.pgs: Dict[bytes, dict] = {}
        self.control_port = 0
        self.transfer_port = 0
        self.lock = threading.RLock()
        self.objects: Dict[bytes, ObjectEntry] = {}
        self.tasks: Dict[bytes, TaskRecord] = {}
        self.pending_queue: deque = deque()          # TaskRecords to place
        self.actors: Dict[bytes, ActorRecord] = {}
        self.workers: Dict[bytes, WorkerHandle] = {}
        self.resources_total = dict(resources)
        self.resources_avail = dict(resources)
        from ray_tpu._private.accelerators import ChipAllocator
        self._chip_alloc = ChipAllocator(int(resources.get("TPU", 0)))
        self._conns: List[_ConnCtx] = []
        self._conn_threads: List[threading.Thread] = []
        self._pull_threads: List[threading.Thread] = []
        self._shutdown = False
        self._listener: Optional[socket.socket] = None
        self._next_worker_seq = 0
        self._deadline_waiters: List[Tuple[float, Callable[[], None]]] = []
        # Wakes _monitor_loop out of its wait: set by shutdown() and by
        # _add_deadline_waiter for deadlines nearer than the tick.
        self._monitor_wake = threading.Event()
        self._max_workers = int(os.environ.get(
            "RAY_TPU_MAX_WORKERS", max(8, int(resources.get("CPU", 4)) * 2)))
        # Circuit breaker: consecutive workers that died before ever
        # registering.  When tripped, stop respawning and fail pending
        # work instead of fork-bombing on a broken environment.
        self._spawn_failures = 0
        self._spawn_failure_limit = 5
        # Dead workers whose processes haven't exited yet; their shm pins
        # are reaped once the process is observed gone (escalating to
        # SIGKILL past the deadline).
        self._pending_reaps: List[Tuple[subprocess.Popen, int, float]] = []
        # Aggregated application metrics pushed by workers/driver
        # (reference: _private/metrics_agent.py aggregation role).
        # key = (name, kind, frozenset(tag items)) -> series dict.
        self._metrics: Dict[tuple, dict] = {}
        # Control-plane RPC server telemetry: per-method latency
        # aggregates + the in-flight handler registry the slow-RPC
        # sentinel sweeps.  Own lock — the dispatch wrapper must not
        # contend with self.lock (most handlers take it themselves).
        from ray_tpu.util import metrics as _metrics_mod
        self._rpc_buckets = _metrics_mod.RPC_SERVER_BUCKETS
        self._rpc_lock = threading.Lock()
        # method -> {"buckets", "sum", "count", "inflight", "slow",
        #            "last_capture"}
        self._rpc_stats: Dict[str, dict] = {}
        # token -> {"method", "t0" (perf_counter), "tid", "msg",
        #           "flagged"} for handlers currently executing.
        self._rpc_inflight: Dict[int, dict] = {}
        self._rpc_token = 0
        # Last successful GCS round-trip (heartbeat loop) — the
        # doctor's GCS-outage signal: the heartbeat thread blocks on a
        # dead GCS, so this age grows during an outage.
        self._gcs_last_ok = time.time()
        # Scheduler decision tracing: bounded recent-decision ring +
        # cumulative outcome counts + the rate-limited `sched.decide`
        # span accumulator.  All mutated under self.lock (the
        # scheduler already holds it at every decision point).
        self._sched_recent: deque = deque(maxlen=50)
        self._sched_outcomes: Dict[str, int] = {}
        # task_ids already counted for a non-terminal outcome
        # (queue/drain_handback) — one count per queue episode, not
        # one per scheduling pass.
        self._sched_noted: set = set()
        self._sched_span: Dict[str, int] = {}
        self._sched_span_t0 = 0.0
        self._next_sched_span = 0.0
        # Spill-candidate detail stashed by _pick_spill_target for the
        # decision ring (scores of the nodes considered).
        self._sched_last_spill: Optional[dict] = None
        # Metrics history ring: (name, kind, tags) -> deque of
        # (ts, value) samples, recorded by the monitor loop at
        # metrics_history_resolution_s cadence (state.metric_history).
        self._metrics_history: Dict[tuple, deque] = {}
        # Worker stdout/stderr capture: per-file read offsets for the
        # log tailer that forwards new lines to the driver console
        # (reference: log_monitor.py `log_to_driver`).
        self._log_dir = os.path.join(session_dir, "logs")
        self._log_offsets: Dict[str, int] = {}
        # Profile/trace event ring (reference: profile events table
        # behind ray.timeline); workers attach execution spans to
        # task_done and push custom spans via profile_event.  Bounded:
        # appends go through _emit_event so evictions are counted
        # (ray_tpu_events_dropped_total) instead of silent.
        self._events: deque = deque(
            maxlen=(config.event_ring_capacity
                    or config.profile_events_max))
        # Scrape-time cache for the per-kind object-byte gauges: a
        # Prometheus scrape must not re-walk a 100k-entry directory
        # under the lock every few seconds.
        self._mem_kind_cache: Tuple[float, dict] = (0.0, {})
        # Objects a draining peer asked this node to adopt: their pull
        # registration marks the entry as a drain replica for the
        # memory-accounting plane.
        self._drain_replica_oids: set = set()
        # Streaming-generator item tables, keyed by the generator's
        # completion object id: {"items": [oid...], "done": bool}
        # (reference: streaming generator object refs in task_manager).
        self._streams: Dict[bytes, dict] = {}
        # Per-(destination, channel-key) compiled-DAG forwarder queues.
        self._chan_fwd_queues: Dict[tuple, Any] = {}
        # Cross-node channel items forwarded, by path ("stream" = the
        # persistent transfer-plane edge, "rpc" = legacy per-item
        # control-plane fallback) — state-dump visibility that the
        # steady-state path stays off the control plane.
        self._dag_items: Dict[str, int] = {}
        # In-flight on-demand stack dumps: token -> collection record.
        self._stack_dumps: Dict[bytes, dict] = {}
        # stream_id -> home node for streaming calls on REMOTE actors:
        # the item table lives on the actor's node; stream_next/release
        # proxy there (cross-node streaming generators).
        self._remote_streams: Dict[bytes, bytes] = {}
        # Compiled-DAG channel queues (cross-node channel plane;
        # reference: experimental/channel/shared_memory_channel.py for
        # same-host, torch_tensor_nccl_channel.py for cross-host).  A
        # queue lives on the CONSUMER's node; producers anywhere
        # chan_send to it (forwarded node-to-node when remote) with
        # bounded capacity + parked-reply backpressure.
        self._dag_queues: Dict[bytes, dict] = {}
        # Graceful-drain state (node_drain.DrainMixin).
        self._init_drain_state()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        from ray_tpu._private.shm_store import ShmObjectStore
        ShmObjectStore(self.store_path, self.store_capacity,
                       create=True).close()
        if config.object_store_prefault:
            self._prefault_store()
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._native_init()     # C++ worker registry (node_native) —
                                # before any conn can register
        self._listener.bind(self.socket_path)
        self._listener.listen(128)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rtpu-node-accept")
        self._accept_thread.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="rtpu-node-monitor")
        self._monitor_thread.start()
        os.makedirs(self._log_dir, exist_ok=True)
        if config.log_to_driver:
            self._log_tail_thread = threading.Thread(
                target=self._log_tail_loop, daemon=True,
                name="rtpu-log-tailer")
            self._log_tail_thread.start()
        if self.multinode:
            self._start_multinode()
        self._start_agent()     # per-node dashboard agent (node_agent)
        # The accept/monitor threads are already running here: worker
        # prestart mutates self.workers like any other spawn path.
        with self.lock:
            for _ in range(config.worker_pool_prestart):
                self._spawn_worker(tpu=False)

    def shutdown(self) -> None:
        with self.lock:
            self._shutdown = True
            workers = list(self.workers.values())
        self._monitor_wake.set()    # don't pay a last monitor sleep
        with self._pull_cond:       # wake parked pull-pool workers
            self._pull_cond.notify_all()
        # Wake the accept loop(s) with a dummy connection and JOIN them
        # BEFORE closing the listener fds.  A thread left blocked in
        # accept() survives close(); when the fd number is reused by the
        # next session's listener, an EINTR retry (SIGCHLD from dying
        # workers) can make the stale thread steal and instantly drop the
        # new session's first connection (BrokenPipe on register_client).
        self._wake_and_join_acceptors()
        for w in workers:
            if w.conn_send:
                try:
                    w.conn_send({"type": "exit"})
                except Exception:
                    pass
        deadline = time.time() + 2.0
        for w in workers:
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(0.05, deadline - time.time()))
            except subprocess.TimeoutExpired:
                w.proc.kill()
        if self._listener:
            self._listener.close()
        if self.multinode:
            try:
                self._peer_listener.close()
            except Exception:
                pass
            if getattr(self, "_transfer_listener", None) is not None:
                try:
                    self._transfer_listener.close()
                except Exception:
                    pass
            with self._peer_lock:
                conns = list(self._peer_conns.values())
                self._peer_conns.clear()
            for c in conns:
                try:
                    c.close()
                except Exception:
                    pass
            try:
                self.gcs.close()
            except Exception:
                pass
        # Join every thread that can touch the shm store BEFORE the
        # caller (ray_tpu.shutdown) closes/munmaps the store client: a
        # straggler conn thread reaping a dead worker against an
        # unmapped segment is a segfault, not an exception.
        with self.lock:
            conns = list(self._conns)
            threads = list(self._conn_threads)
            pulls = list(self._pull_threads)
        for ctx in conns:
            try:
                ctx.sock.close()
            except OSError:
                pass
        deadline = time.time() + 3.0
        for t in threads + pulls + [
                getattr(self, "_monitor_thread", None),
                getattr(self, "_gcs_event_thread", None),
                # Log tailer reads worker-log files on a 0.25s tick; a
                # straggler touching the log dir after teardown was an
                # RT014 self-finding (it observes _shutdown, so this
                # join is bounded by one tick).
                getattr(self, "_log_tail_thread", None)]:
            if t is None or not t.is_alive():
                continue
            t.join(timeout=max(0.05, deadline - time.time()))
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        try:
            os.unlink(self.store_path)
        except OSError:
            pass
        with self._spill_fd_lock:
            fds, self._spill_fds = list(self._spill_fds.values()), {}
        for fd, _ in fds:
            try:
                os.close(fd)
            except OSError:
                pass
            leaksan.discharge("spill_fd", fd, expect=False)

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------
    def _prefault_store(self) -> None:
        """Write-touch every page of the freshly created store so a
        put's single memcpy never pays first-touch tmpfs page faults
        (measured ~4x: 1.6 -> 6 GB/s on this host).  Safe ONLY here:
        no client has connected yet, so the value-preserving
        read-modify-write cannot race an allocator update."""
        import mmap as _mmap
        try:
            with open(self.store_path, "r+b") as f:
                mm = _mmap.mmap(f.fileno(), 0)
                mv = memoryview(mm)
                for off in range(0, len(mv), _mmap.PAGESIZE):
                    mv[off] = mv[off]
                del mv
                mm.close()
        except (OSError, ValueError):
            pass

    def _wake_and_join_acceptors(self) -> None:
        from ray_tpu._private.protocol import wake_and_join_acceptor
        wake_and_join_acceptor(getattr(self, "_accept_thread", None),
                               socket.AF_UNIX, self.socket_path)
        if self.multinode:
            wake_and_join_acceptor(
                getattr(self, "_peer_accept_thread", None),
                socket.AF_INET, (self.host, self.control_port))
            if getattr(self, "_transfer_listener", None) is not None:
                wake_and_join_acceptor(
                    getattr(self, "_transfer_accept_thread", None),
                    socket.AF_INET, (self.host, self.transfer_port))

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            if self._shutdown:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            ctx = _ConnCtx(sock)
            t = threading.Thread(target=self._conn_loop, args=(ctx,),
                                 daemon=True, name="rtpu-node-conn")
            with self.lock:
                self._conns.append(ctx)
                self._conn_threads.append(t)
                if len(self._conn_threads) > 64:
                    self._conn_threads = [x for x in self._conn_threads
                                          if x.is_alive()]
            t.start()

    def _conn_loop(self, ctx: _ConnCtx) -> None:
        try:
            while not self._shutdown:
                msg = recv_msg(ctx.sock)
                self._dispatch(ctx, msg)
        except (ConnectionLost, OSError, EOFError):
            pass
        finally:
            self._on_disconnect(ctx)

    def _dispatch(self, ctx: _ConnCtx, msg: dict) -> None:
        mtype = msg["type"]
        handler = getattr(self, "_h_" + mtype, None)
        if handler is None:
            if "__req_id__" in msg:
                ctx.reply(msg, {"__error__": f"unknown rpc {mtype}"})
            return
        token = self._rpc_begin(mtype, msg)
        try:
            # Server-side chaos delay (site "rpc.<type>"): the
            # protocol-layer injector fires SENDER-side, which a
            # server-latency histogram never sees — this hook is what
            # makes slow-handler drills (and the slow-RPC sentinel
            # test) injectable.  fire_spec has a cheap disabled-path
            # early-out, so the hot path pays one attribute read.
            spec = chaos.fire_spec("rpc." + mtype, "delay")
            if spec is not None:
                lo = float(spec.get("lo_ms") or 0.0)
                hi = float(spec.get("hi_ms") or lo)
                time.sleep((lo + (hi - lo) * chaos.jitter()) / 1000.0)
            handler(ctx, msg)
        except Exception as e:  # handler bug — surface to caller
            if "__req_id__" in msg:
                ctx.reply(msg, {"__error__": e})
        finally:
            self._rpc_end(mtype, token)

    # ------------------------------------------------------------------
    # control-plane RPC server telemetry (tentpole of PR 16): every
    # dispatched handler lands in a per-method latency aggregate
    # (ray_tpu_rpc_server_seconds{method}), an in-flight registry the
    # slow-RPC sentinel sweeps, and — for listeners outside _dispatch
    # (transfer chunks, stream delivery) — the _rpc_record fold-in.
    # All under a dedicated _rpc_lock: ~two uncontended acquires per
    # RPC, never self.lock (the PR-8 hot-path rule).
    # ------------------------------------------------------------------
    def _rpc_stat_locked(self, method: str) -> dict:
        """Per-method aggregate cell (create-once).  Caller holds
        self._rpc_lock."""
        st = self._rpc_stats.get(method)
        if st is None:
            st = {"buckets": {str(b): 0 for b in self._rpc_buckets},
                  "sum": 0.0, "count": 0, "inflight": 0,
                  "slow": 0, "last_capture": 0.0}
            self._rpc_stats[method] = st
        return st

    def _rpc_begin(self, method: str, msg: dict) -> int:
        with self._rpc_lock:
            self._rpc_token += 1
            token = self._rpc_token
            self._rpc_stat_locked(method)["inflight"] += 1
            self._rpc_inflight[token] = {
                "method": method, "t0": time.perf_counter(),
                "tid": threading.get_ident(), "msg": msg,
                "flagged": False}
        return token

    def _rpc_end(self, method: str, token: int) -> None:
        end = time.perf_counter()
        with self._rpc_lock:
            entry = self._rpc_inflight.pop(token, None)
            if entry is None:
                return
            st = self._rpc_stat_locked(method)
            st["inflight"] = max(st["inflight"] - 1, 0)
            dur = end - entry["t0"]
            for b in self._rpc_buckets:
                if dur <= b:
                    st["buckets"][str(b)] += 1
                    break
            st["sum"] += dur
            st["count"] += 1

    def _rpc_record(self, method: str, dur: float) -> None:
        """Fold one completed handler duration into the per-method
        aggregates, for serving loops that don't route through
        _dispatch (transfer-plane chunk serving, DAG stream
        delivery)."""
        with self._rpc_lock:
            st = self._rpc_stat_locked(method)
            for b in self._rpc_buckets:
                if dur <= b:
                    st["buckets"][str(b)] += 1
                    break
            st["sum"] += dur
            st["count"] += 1

    def _slow_rpc_tick(self) -> None:
        """Monitor-loop sweep over in-flight handlers: flag anything
        past max(slow_rpc_min_seconds, slow_rpc_p95_multiple * that
        method's server-side p95) — the stall sentinel's contract at
        RPC scale.  Flag under _rpc_lock, capture OUTSIDE it; at most
        one stack+args capture per method per capture window."""
        floor = config.slow_rpc_min_seconds
        if floor <= 0:
            return
        from ray_tpu.util.metrics import hist_quantile
        now = time.perf_counter()
        wall = time.time()
        flagged = []
        with self._rpc_lock:
            for entry in self._rpc_inflight.values():
                if entry["flagged"]:
                    continue
                st = self._rpc_stats.get(entry["method"])
                threshold = floor
                if st is not None and \
                        st["count"] >= config.slow_rpc_min_samples:
                    threshold = max(
                        floor, config.slow_rpc_p95_multiple
                        * hist_quantile(st, 0.95))
                elapsed = now - entry["t0"]
                if elapsed < threshold:
                    continue
                entry["flagged"] = True
                st = self._rpc_stat_locked(entry["method"])
                st["slow"] += 1
                capture = (wall - st["last_capture"]
                           >= config.slow_rpc_capture_window_s)
                if capture:
                    st["last_capture"] = wall
                flagged.append((entry, elapsed, threshold, capture))
        for entry, elapsed, threshold, capture in flagged:
            from ray_tpu.util.metrics import SLOW_RPC_METRIC
            with self.lock:
                self._inc_counter(
                    SLOW_RPC_METRIC, {"method": entry["method"]},
                    "control-plane handlers flagged by the slow-RPC "
                    "sentinel")
            if capture:
                self._capture_slow_rpc(entry, elapsed, threshold)

    def _capture_slow_rpc(self, entry: dict, elapsed: float,
                          threshold: float) -> None:
        """One stack + args-summary capture of a flagged handler's
        thread, recorded as a `slow_rpc` timeline event (surfaced by
        profiling.timeline() and `ray_tpu doctor`)."""
        import traceback
        frame = sys._current_frames().get(entry["tid"])
        stack = ("".join(traceback.format_stack(frame))
                 if frame is not None else "")
        now = time.time()
        self._emit_event({
            "kind": "slow_rpc",
            "name": "rpc." + entry["method"] + ":slow",
            "method": entry["method"],
            "elapsed_s": round(elapsed, 4),
            "threshold_s": round(threshold, 4),
            "stack": stack,
            "rpc_args": _rpc_args_summary(entry.get("msg") or {}),
            "pid": os.getpid(),
            "start": now, "end": now,
            "node_id": self.node_id.hex(),
        })

    def _on_disconnect(self, ctx: _ConnCtx) -> None:
        self._native_on_disconnect(ctx)
        with self.lock:
            if ctx in self._conns:
                self._conns.remove(ctx)
            w = ctx.worker
            if w is None or w.state == "dead":
                return
            self._handle_worker_death(w, "worker connection lost")
            self._schedule()

    # ------------------------------------------------------------------
    # multi-node plane (reference: object_manager.h:117 transfer,
    # cluster_task_manager.h:42 spillback, ray_syncer.h:88 resource sync)
    # ------------------------------------------------------------------
    def _start_multinode(self) -> None:
        """Open the peer TCP listener, register with the GCS, start the
        heartbeat + event threads."""
        self._peer_listener = socket.socket(socket.AF_INET,
                                            socket.SOCK_STREAM)
        self._peer_listener.setsockopt(socket.SOL_SOCKET,
                                       socket.SO_REUSEADDR, 1)
        host = os.environ.get("RAY_TPU_NODE_HOST", "127.0.0.1")
        self._peer_listener.bind((host, 0))
        self._peer_listener.listen(64)
        self.host = host
        self.control_port = self._peer_listener.getsockname()[1]
        self._peer_accept_thread = threading.Thread(
            target=self._peer_accept_loop, daemon=True,
            name="rtpu-peer-accept")
        self._peer_accept_thread.start()
        # Dedicated object-transfer listener: raw binary chunk streams
        # (node_objects._transfer_serve_loop), kept OFF the pickled
        # control-plane listener so bulk data never queues behind
        # control rpcs (reference: object_manager.h transfer plane).
        try:
            self._transfer_listener = socket.socket(socket.AF_INET,
                                                    socket.SOCK_STREAM)
            self._transfer_listener.setsockopt(socket.SOL_SOCKET,
                                               socket.SO_REUSEADDR, 1)
            self._transfer_listener.bind((host, 0))
            self._transfer_listener.listen(64)
            self.transfer_port = \
                self._transfer_listener.getsockname()[1]
            self._transfer_accept_thread = threading.Thread(
                target=self._transfer_accept_loop, daemon=True,
                name="rtpu-xfer-accept")
            self._transfer_accept_thread.start()
        except OSError:
            # No transfer listener: advertise the control port so peers
            # fall back to the control-plane chunk RPCs.
            self._transfer_listener = None
            self.transfer_port = self.control_port
        self._gcs_event_thread = threading.Thread(
            target=self._gcs_event_loop, daemon=True,
            name="rtpu-gcs-events")
        self._gcs_event_thread.start()
        self.gcs.register_node(self.node_id, host, self.control_port,
                               self.transfer_port, self.resources_total)
        self._gcs_epoch = self.gcs.gcs_epoch
        self.gcs.sub_nodes(lambda ev, info:
                           self._gcs_events.put(("node", ev, info)))
        self._cluster_view = self.gcs.nodes()
        threading.Thread(target=self._heartbeat_loop, daemon=True,
                         name="rtpu-heartbeat").start()

    def _peer_accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _ = self._peer_listener.accept()
            except OSError:
                return
            if self._shutdown:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            ctx = _ConnCtx(sock)
            ctx.kind = "peer"
            t = threading.Thread(target=self._conn_loop, args=(ctx,),
                                 daemon=True, name="rtpu-peer-conn")
            with self.lock:
                self._conns.append(ctx)
                self._conn_threads.append(t)
                if len(self._conn_threads) > 64:
                    self._conn_threads = [x for x in self._conn_threads
                                          if x.is_alive()]
            t.start()

    def _heartbeat_loop(self) -> None:
        interval = config.heartbeat_interval_s
        while not self._shutdown:
            try:
                with self.lock:
                    avail = dict(self.resources_avail)
                    # Demand/idleness signal for the autoscaler
                    # (reference: resource_demand in raylet heartbeats →
                    # autoscaler/_private/monitor.py).
                    shapes = [dict(r.spec.get("resources") or {})
                              for r in list(self.pending_queue)[:20]]
                    busy = any(w.state in ("busy", "blocked")
                               for w in self.workers.values())
                    if shapes or busy:
                        self._idle_since = None
                    elif getattr(self, "_idle_since", None) is None:
                        self._idle_since = time.time()
                    # Pending placement-group demand (gang shapes the
                    # autoscaler must bin-pack into whole node sets;
                    # reference: resource_demand_scheduler PG demand).
                    pg_demand = [
                        {"pg_id": pid.hex(),
                         "bundles": [dict(b) for b in r["bundles"]],
                         "strategy": r["strategy"]}
                        for pid, r in self.pgs.items()
                        if r["state"] == "pending"][:8]
                    load = {"pending": len(self.pending_queue),
                            "shapes": shapes,
                            "pg_demand": pg_demand,
                            "idle_since": self._idle_since}
                self.gcs.heartbeat(self.node_id, avail, load)
                # Doctor's GCS-outage signal: this thread blocks (or
                # raises) on a dead GCS, so the age of the last
                # successful round-trip grows during an outage.
                self._gcs_last_ok = time.time()
                # Autoscaler lease (StandardAutoscaler refreshes a
                # timestamp in GCS KV every reconcile): gates infeasible
                # fail-fast vs wait.  A stale lease (dead autoscaler)
                # must NOT leave infeasible work pending forever.
                try:
                    raw = self.gcs.kv_get("cluster", b"autoscaler")
                    self._autoscaler_lease = (float(raw) if raw else 0.0)
                except Exception:
                    pass
                self._cluster_view = self.gcs.nodes()
                # Control-plane status card (epoch / WAL size /
                # last-snapshot age): polled at a slow cadence for the
                # ray_tpu_gcs_wal_bytes gauge and `ray_tpu gcs`.
                if time.time() >= self._next_gcs_status:
                    self._next_gcs_status = (time.time()
                                             + config.gcs_status_interval_s)
                    try:
                        self._gcs_status = self.gcs.status()
                    except Exception:
                        pass
                with self.lock:
                    self._schedule()   # peer capacity may have freed up
            except Exception:
                pass
            time.sleep(interval * 0.5)

    def _gcs_event_loop(self) -> None:
        while not self._shutdown:
            try:
                item = self._gcs_events.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                if item[0] == "node":
                    self._on_node_event(item[1], item[2])
                elif item[0] == "push":
                    self._on_gcs_push(item[1])
                elif item[0] == "resync":
                    self._gcs_resync()
            except Exception:
                pass

    def _on_gcs_push(self, msg: dict) -> None:
        if msg.get("type") == "object_deleted":
            # Owner-driven delete of an object we hold a foreign copy of.
            oid = msg["object_id"]
            with self.lock:
                e = self.objects.get(oid)
                if e is None or not e.foreign:
                    return
                was_shm = e.loc == "shm"
                if e.waiters:
                    # Someone on this node is blocked in get(): turn the
                    # entry into a lost-tombstone and wake them, instead
                    # of hanging them forever on a popped entry.
                    blob = ser.dumps(exc.ObjectLostError(
                        oid.hex(), "deleted by owner while being read"))
                    e.state = FAILED
                    e.loc, e.data, e.size = "error", blob, len(blob)
                    waiters, e.waiters = e.waiters, []
                    for wake in waiters:
                        wake()
                else:
                    self.objects.pop(oid, None)
                    e.deleted = True
            if was_shm:
                try:
                    store = self._store()
                    store.release(_OID(oid))
                    store.delete(_OID(oid))
                except Exception:
                    pass

    def _on_node_event(self, event: str, info: dict) -> None:
        nid = info["node_id"]
        if event == "node_added":
            if nid != self.node_id:
                try:
                    self._cluster_view = self.gcs.nodes()
                except Exception:
                    pass
                with self.lock:
                    self._schedule()
            return
        if event == "node_draining":
            if nid == self.node_id:
                # GCS-initiated drain of THIS node (CLI / operator):
                # the GCS already flipped the state — don't re-publish.
                self._begin_drain("gcs",
                                  info.get("reason") or "drain requested",
                                  grace_s=info.get("grace_s"),
                                  publish=False)
            else:
                # Stop targeting the draining peer immediately (the
                # heartbeat refresh would catch up within ~0.5s, but
                # every task spilled there in the window is a task it
                # must hand back).
                for n in self._cluster_view:
                    if n["node_id"] == nid:
                        n["state"] = "draining"
            return
        if event != "node_dead" or nid == self.node_id:
            return
        with self._peer_lock:
            conn = self._peer_conns.pop(nid, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        self._cluster_view = [n for n in self._cluster_view
                              if n["node_id"] != nid]
        # Committed placement groups with bundles on the dead node get
        # re-placed whole (node_pg.py _pg_on_node_dead).
        try:
            self._pg_on_node_dead(nid)
        except Exception:
            pass
        # Tombstone every actor the GCS knew lived there, plus our hints.
        dead_reason = f"node {nid.hex()[:8]} died: " \
                      f"{info.get('reason') or 'lost heartbeats'}"
        retry, fail, pull_check = [], [], []
        dead_actors = set(info.get("dead_actors", ()))
        with self.lock:
            for aid in dead_actors:
                self._remote_actor_tombstones[aid] = dead_reason
            for aid, home in list(self._actor_homes.items()):
                if home == nid:
                    # Drop the stale hint always; tombstone only actors
                    # the GCS confirms died THERE — an actor migrated
                    # off a drained node lives elsewhere now (the GCS
                    # directory was re-pointed via set_actor_node), and
                    # the next call re-resolves it.
                    del self._actor_homes[aid]
                    if aid in dead_actors:
                        self._remote_actor_tombstones[aid] = dead_reason
            for tid, (rec, target) in list(self.forwarded.items()):
                if target != nid:
                    continue
                del self.forwarded[tid]
                pull_check.append(rec)
        # A forwarded task may have completed before the node died — its
        # returns are then in the GCS (inline) or on surviving replicas.
        # Only tasks with no published results are retried/failed.
        reconstruct: List[TaskRecord] = []
        for rec in pull_check:
            statuses = []
            for oid in rec.spec["return_ids"]:
                try:
                    locs = self.gcs.get_locations(oid)
                except Exception:
                    locs = {}
                statuses.append(
                    "ready" if locs.get("kind") is not None
                    else "lost" if locs.get("lost") else "missing")
            if all(s == "ready" for s in statuses):
                with self.lock:
                    # Completed remotely but the forward_done notify was
                    # lost with the node: release the owner-side holds
                    # here (forwarded entry already popped above).
                    for dep in rec.spec.get("embedded") or []:
                        self._decref(dep)
                    for oid in rec.spec["return_ids"]:
                        self._ensure_pull(oid)
                continue
            if (all(s in ("ready", "lost") for s in statuses)
                    and rec.actor_id is None):
                # Completed, but the only copies died with the node
                # (the GCS lost-marker proves it WAS ready): re-running
                # is lineage reconstruction, budgeted by
                # max_object_reconstructions — independent of the
                # task's retry policy, which governs never-ran work.
                reconstruct.append(rec)
                continue
            (retry if rec.retries_left > 0
             and not rec.is_actor_creation else fail).append(rec)
        with self.lock:
            for rec in retry:
                self._schedule_retry(rec, "node_death", dead_reason)
            for rec in reconstruct:
                if not self._requeue_as_reconstruction(rec,
                                                       dead_reason):
                    fail.append(rec)
            for rec in fail:
                if rec.actor_id is not None and not rec.is_actor_creation:
                    err: Exception = exc.ActorDiedError(
                        rec.actor_id.hex(), dead_reason)
                else:
                    err = exc.WorkerCrashedError(
                        f"{dead_reason} while running "
                        f"{rec.spec.get('name')}")
                self._fail_task_returns(rec, err)
                if rec.is_actor_creation:
                    # _fail_task_returns keeps creation holds for restart
                    # replay — but this actor's node is gone for good.
                    for dep in rec.spec.get("embedded") or []:
                        self._decref(dep)
            self._schedule()

    def _gcs_resync(self) -> None:
        """Bulk re-publication of this node's authoritative local state
        to the GCS after a reconnect (re-sync half of the GCS restart
        protocol; reference: raylet resubscription rebuilding a
        restarted GCS).  Re-registers the node, re-announces every
        READY object copy this node serves (the GCS object directory is
        soft state), re-points the actor directory at resident actors,
        and restores an in-progress drain.  Idempotent — runs on every
        reconnect, restart or not."""
        if not self.multinode or self._shutdown:
            return
        t0 = time.time()
        objs: List[Tuple[bytes, int]] = []
        inline: List[Tuple[bytes, int, str, bytes]] = []
        with self.lock:
            for oid, e in self.objects.items():
                if e.state not in (READY, FAILED) or e.deleted:
                    continue
                if e.foreign and e.loc != "shm":
                    continue    # pulled inline copies: record not ours
                if e.loc in ("shm", "spilled", "inline"):
                    # Same publication rule as task_done: local values
                    # (including spilled ones this node still serves)
                    # announce a holder; readers fetch from here.
                    objs.append((oid, e.size))
                elif e.loc == "error" and e.data is not None:
                    # Error blobs ride in the GCS record itself so they
                    # survive this node's death too.
                    inline.append((oid, e.size, "error", bytes(e.data)))
            actors = [aid for aid, a in self.actors.items()
                      if a.state != "dead"]
            draining = None
            if self.draining:
                draining = {"deadline": self._drain_deadline,
                            "reason": self._drain_reason}
            resources_total = dict(self.resources_total)
        try:
            out = self.gcs.resync_node(
                self.node_id, self.host, self.control_port,
                self.transfer_port, resources_total,
                objects=objs, inline=inline, actors=actors,
                draining=draining)
        except Exception:
            return      # still down; the next reconnect resyncs
        dt = time.time() - t0
        new_epoch = out.get("epoch") or self.gcs.gcs_epoch
        restarted = (new_epoch is not None
                     and self._gcs_epoch is not None
                     and new_epoch != self._gcs_epoch)
        self._gcs_epoch = new_epoch
        from ray_tpu.util.metrics import (GCS_RESTARTS_METRIC,
                                          GCS_RESYNC_BUCKETS,
                                          GCS_RESYNC_SECONDS_METRIC)
        with self.lock:
            self._observe_hist(GCS_RESYNC_SECONDS_METRIC, {}, dt,
                               GCS_RESYNC_BUCKETS,
                               "node-side GCS re-sync duration")
            if restarted:
                self._inc_counter(GCS_RESTARTS_METRIC, {},
                                  "GCS restarts observed (recovery "
                                  "epoch bumps)")
        if restarted:
            # Lifecycle event: surfaces in summarize_tasks() under
            # "node:gcs_restart" and in the timeline (like drains).
            self._emit_event({
                "kind": "gcs_restart", "name": "gcs:restart",
                "epoch": new_epoch, "resync_s": dt,
                "objects_republished": len(objs) + len(inline),
                "actors_republished": len(actors),
                "start": t0, "end": time.time(),
                "pid": 0, "node_id": self.node_id.hex()})
        self._next_gcs_status = 0.0     # refresh the status card now
        try:
            self._cluster_view = self.gcs.nodes()
        except Exception:
            pass
        with self.lock:
            self._schedule()

    # -- peer connections --------------------------------------------------
    def _peer_conn_to(self, ninfo: dict):
        """Get (or open) the persistent Connection to a peer node."""
        from ray_tpu._private.protocol import Connection, connect_tcp
        nid = ninfo["node_id"]
        if chaos.partitioned(nid):
            # Node-partition fault: this node cannot reach the target —
            # covers control forwards AND object transfer, since both
            # ride these peer connections.
            raise ConnectionLost(
                f"chaos: partitioned from node {nid.hex()[:12]}")
        with self._peer_lock:
            conn = self._peer_conns.get(nid)
            if conn is not None and not conn._closed:
                return conn
        sock = connect_tcp(ninfo["host"], ninfo["control_port"],
                           deadline_s=5.0)
        conn = Connection(sock)
        with self._peer_lock:
            existing = self._peer_conns.get(nid)
            if existing is not None and not existing._closed:
                conn.close()
                return existing
            self._peer_conns[nid] = conn
        return conn

    def _node_info(self, nid: bytes) -> Optional[dict]:
        for n in self._cluster_view:
            if n["node_id"] == nid:
                return n
        try:
            # Bounded: this runs on conn/forward threads whose serial
            # dispatch must not wedge through a GCS outage — the cached
            # view above is the ride-it-out answer.
            self._cluster_view = self.gcs.nodes(max_wait_s=2.0)
        except Exception:
            return None
        for n in self._cluster_view:
            if n["node_id"] == nid:
                return n
        return None

    # ------------------------------------------------------------------
    # message handlers (all named _h_<type>)
    # ------------------------------------------------------------------
    def _h_register_client(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            ctx.kind = m["kind"]
            ctx.client_id = m["client_id"]
            ctx.pid = m.get("pid", 0)
            if m["kind"] == "worker":
                w = self.workers.get(m["client_id"])
                if w is None:
                    ctx.reply(m, {"__error__": "unknown worker"})
                    return
                ctx.worker = w
                w.conn_send = ctx.send
                w.state = "idle"
                w.last_idle_time = time.time()
                self._spawn_failures = 0
                self._schedule()
            ctx.reply(m, {"ok": True,
                          "store_path": self.store_path,
                          "session_dir": self.session_dir})

    def _infeasible_reason(self, res: Dict[str, float]) -> Optional[str]:
        """A request no node total can ever satisfy hangs forever unless
        rejected up front (reference: raylet infeasible-task errors).
        Multi-node: feasible if ANY alive node's totals cover it."""
        if not res:
            return None
        if self._local_totals_satisfy(res):
            return None
        if self.multinode:
            for n in self._cluster_view:
                if n.get("state") != "alive":
                    continue
                if all(v <= n["resources_total"].get(k, 0.0) + 1e-9
                       for k, v in res.items()):
                    return None
        return (f"resource request {res} exceeds every node's total "
                f"(local total: {self.resources_total})")

    def _h_submit_task(self, ctx: _ConnCtx, m: dict) -> None:
        spec = m["spec"]
        aid = spec.get("actor_id")
        home: Optional[bytes] = None
        if (aid is not None and not spec.get("is_actor_creation")
                and self.multinode):
            with self.lock:
                local = aid in self.actors
                home = self._actor_homes.get(aid)
            if not local and home is None:
                # Actor created elsewhere (e.g. found via get_actor):
                # resolve its home through the GCS actor directory.
                # No self.lock held — gcs.call would deadlock under it.
                try:
                    home = self.gcs.get_actor_node(aid)
                except Exception:
                    home = None
                if home is not None:
                    self._actor_homes[aid] = home
            if not local and home is not None:
                ninfo = self._cluster_node(home)
                if ninfo is None or ninfo.get("state") != "alive":
                    # Stale hint: the cached home is draining or gone —
                    # the actor may have MIGRATED (drain restarts actors
                    # elsewhere and re-points the GCS directory).
                    try:
                        fresh = self.gcs.get_actor_node(aid)
                    except Exception:
                        fresh = None
                    if fresh is not None and fresh != home:
                        home = fresh
                        self._actor_homes[aid] = home
        with self.lock:
            if (aid is not None and aid not in self.actors
                    and self.multinode):
                tomb = self._remote_actor_tombstones.get(aid)
                if tomb is not None:
                    rec = TaskRecord(spec)
                    self.tasks[rec.task_id] = rec
                    for oid in spec["return_ids"]:
                        self.objects.setdefault(oid, ObjectEntry())
                    self._fail_task_returns(rec, exc.ActorDiedError(
                        aid.hex(), tomb, task_started=False))
                    ctx.reply(m, {"ok": True})
                    return
                if home is not None and home != self.node_id:
                    rec = TaskRecord(spec)
                    if spec.get("streaming"):
                        # Remote-actor stream: the item table fills on
                        # the actor's HOME node; remember where so
                        # stream_next/release from local consumers
                        # proxy there (items themselves are ordinary
                        # GCS-located objects and pull across).
                        self._remote_streams[
                            spec["return_ids"][0]] = home
                    # Remote actor call: forward to its home node; results
                    # come back through the GCS location directory.
                    self.tasks[rec.task_id] = rec
                    for oid in spec["return_ids"]:
                        e = self.objects.setdefault(oid, ObjectEntry())
                        e.producing_task = rec.task_id
                    ninfo = self._node_info(home)
                    if ninfo is None:
                        self._fail_task_returns(rec, exc.ActorDiedError(
                            aid.hex(), "actor's node is gone"))
                    else:
                        self._forward_task(rec, ninfo)
                    ctx.reply(m, {"ok": True})
                    return
            rec = TaskRecord(spec)
            # When an autoscaler is live (it announces itself in GCS KV,
            # mirrored into _autoscaler_active by the heartbeat loop), a
            # currently unsatisfiable shape stays PENDING as demand — a
            # node with the resource may be provisioned (reference:
            # infeasible tasks wait and feed the autoscaler).  Otherwise
            # fail fast, cluster-wide totals considered.
            reason = (None if spec.get("pg") is not None
                      or self._autoscaler_live()
                      else self._infeasible_reason(spec.get("resources")))
            if (reason is None and spec.get("streaming")
                    and not self._local_totals_satisfy(
                        spec.get("resources") or {})):
                # Streaming tasks never spill (their item stream is
                # node-local); an unsatisfiable-here request would
                # otherwise hang pending forever.
                reason = ("streaming generator tasks run on the "
                          "submitting node, whose resources cannot "
                          "satisfy this request")
            if reason is not None and spec.get("actor_id") is None:
                self.tasks[rec.task_id] = rec
                for oid in spec["return_ids"]:
                    self.objects.setdefault(oid, ObjectEntry())
                self._fail_task_returns(rec, exc.InfeasibleResourceError(
                    f"task {spec.get('name')!r} is infeasible: {reason}"))
                ctx.reply(m, {"ok": True})
                return
            if self._spawn_failures >= self._spawn_failure_limit:
                self.tasks[rec.task_id] = rec
                for oid in spec["return_ids"]:
                    self.objects.setdefault(oid, ObjectEntry())
                self._fail_task_returns(rec, exc.WorkerCrashedError(
                    "worker environment is broken (spawn circuit breaker "
                    "tripped); task rejected"))
                ctx.reply(m, {"ok": True})
                return
            self.tasks[rec.task_id] = rec
            for oid in spec["return_ids"]:
                entry = self.objects.get(oid)
                if entry is None:
                    entry = ObjectEntry()
                    self.objects[oid] = entry
                entry.producing_task = rec.task_id
            # Drop deps that are already ready.
            rec.deps = {d for d in rec.deps
                        if not self._object_ready(d)}
            if rec.had_deps and not rec.deps:
                rec.stages.setdefault("deps_fetched", time.time())
            if self.multinode:
                # Deps produced on other nodes (earlier spills, remote
                # actors) must be pulled or this task waits forever;
                # _ensure_pull no-ops for locally-producing deps.
                for d in rec.deps:
                    self._ensure_pull(d)
                if rec.deps:
                    # pull_wait checkpoint: transfer-plane share of the
                    # deps_fetch stage (tracing.STAGE_DURATION_PAIRS).
                    rec.stages.setdefault("pull_wait", time.time())
            if rec.actor_id is not None and not rec.is_actor_creation:
                self._enqueue_actor_task(rec)
            else:
                self.pending_queue.append(rec)
            self._schedule()
        ctx.reply(m, {"ok": True})

    def _object_ready(self, oid: bytes) -> bool:
        """Caller holds self.lock."""
        e = self.objects.get(oid)
        return e is not None and e.state in (READY, FAILED)

    def _h_put_object(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            # loc="error" puts deliver an exception as the object's
            # value (Serve failover bridges a final failure this way).
            self._register_object(m["object_id"], m["loc"],
                                  m.get("data"), m["size"],
                                  state=(FAILED if m["loc"] == "error"
                                         else READY),
                                  embedded=m.get("embedded") or [],
                                  creator_pid=ctx.pid,
                                  owner=ctx.client_id)
            self._schedule()
        ctx.reply(m, {"ok": True})

    def _register_object(self, oid: bytes, loc: str,
                         data: Optional[bytes], size: int,
                         state: str = READY,
                         embedded: Optional[List[bytes]] = None,
                         creator_pid: int = 0,
                         foreign: bool = False,
                         owner: Optional[bytes] = None) -> None:
        """Register/overwrite an object directory entry.  Caller
        holds self.lock."""
        if loc == "shm" and creator_pid and creator_pid != os.getpid():
            # Adopt the creator's pin into the directory's ledger so
            # reaping the (possibly dead) creator leaves it pinned.
            from ray_tpu._private import shm_store as shm
            try:
                store = self._store()
                rc = store.transfer_pin(_OID(oid), creator_pid, os.getpid())
                if rc == shm.NOPIN:
                    # The creator died and its pin was already reaped
                    # before this registration drained: take a fresh
                    # directory pin (or declare the object lost if the
                    # unpinned entry was evicted in the gap).
                    if store.get(_OID(oid)) is None:
                        blob = ser.dumps(exc.ObjectLostError(
                            oid.hex(), "evicted before registration "
                            "(creator process died)"))
                        loc, data, size = "error", blob, len(blob)
                        state = FAILED
            except Exception:
                pass
        entry = self.objects.get(oid)
        if entry is None:
            entry = ObjectEntry()
            # Ownership is decided at entry birth and never flips: a
            # pre-existing entry (created at submit/put on the owner)
            # stays owned even when its value arrives via a pull —
            # otherwise owner-driven global delete would be skipped and
            # forwarded-task results would leak cluster-wide.
            entry.foreign = foreign
            self.objects[oid] = entry
        entry.state = state
        entry.loc = loc
        entry.data = data
        entry.size = size
        if owner is not None and entry.owner is None:
            # First writer wins: a pulled replica arriving later must
            # not overwrite the owner recorded at put/submit time.
            entry.owner = owner
        if oid in self._drain_replica_oids:
            # Copy adopted from a draining peer: visible as its own
            # reference kind in the memory plane (it outlives ordinary
            # borrow refcounting — the adopting directory holds it).
            entry.drain_replica = True
            self._drain_replica_oids.discard(oid)
        if loc == "spilled" and data is not None:
            # Born spilled (worker wrote the return to disk because the
            # store was full of in-flight returns): track the file so
            # delete unlinks it and peers can fetch it.
            entry.spill_path = data.decode()
            # Lift any stale no-recache tombstone (oid reborn via
            # reconstruction): the fd cache may serve it again.
            with self._spill_fd_lock:
                self._spill_dead.discard(oid)
        if embedded:
            entry.embedded = list(embedded)
        if self.multinode:
            # A forwarded task's first published return means the remote
            # run completed — stop tracking it for node-death retry.
            if entry.producing_task is not None:
                self._complete_forwarded(entry.producing_task)
            # Publish to the GCS location directory (inline/error payloads
            # ride in the record itself; shm copies announce this node).
            # Pulled inline copies are already in the GCS — skip re-pub.
            if not (foreign and loc != "shm"):
                try:
                    kind = ("error" if state == FAILED
                            else ("inline" if loc == "inline" else "shm"))
                    if kind == "inline" and not entry.foreign:
                        # Local-owned small value: record the location
                        # only — remote readers fetch the payload from
                        # this node via fetch_object_meta.  Shipping
                        # every local put's bytes to the GCS would
                        # mirror the whole store there.
                        self.gcs.add_location(oid, self.node_id, size,
                                              kind="shm", data=None)
                    else:
                        # Cross-node results (foreign entries) and error
                        # blobs carry their payload in the GCS record so
                        # they survive the producing node's death.
                        self.gcs.add_location(
                            oid, self.node_id if kind == "shm" else None,
                            size, kind=kind,
                            data=data if kind != "shm" else None)
                except Exception:
                    pass
        waiters, entry.waiters = entry.waiters, []
        for wake in waiters:
            wake()
        # Unblock tasks waiting on this object.
        now = time.time()
        for rec in list(self.pending_queue):
            if oid in rec.deps:
                rec.deps.discard(oid)
                if not rec.deps:
                    rec.stages.setdefault("deps_fetched", now)
        for actor in self.actors.values():
            touched = False
            for rec in actor.queue:
                if oid in rec.deps:
                    rec.deps.discard(oid)
                    if not rec.deps:
                        rec.stages.setdefault("deps_fetched", now)
                    touched = True
            if touched:
                self._drain_actor_queue(actor)

    def _h_get_objects(self, ctx: _ConnCtx, m: dict) -> None:
        """Blocking get: reply once every requested object is ready."""
        oids: List[bytes] = m["object_ids"]
        if chaos.armed("get_objects", "evict"):
            # Store-eviction fault: vanish a requested READY object's
            # shm payload (directory entry kept READY) so the reader
            # hits the lineage-reconstruction path.  Eligibility is
            # checked BEFORE fire() so a get of inline/lineage-less
            # objects can't burn the budget (and pollute the fault
            # trace) without evicting anything.
            with self.lock:
                eligible = [o for o in oids if self._chaos_evictable(o)]
            if eligible and chaos.fire("get_objects", "evict"):
                with self.lock:
                    for oid in eligible:
                        if self._chaos_evict_entry(oid):
                            break
        timeout = m.get("timeout")
        deadline = time.time() + timeout if timeout is not None else None
        done = threading.Event()   # reply-once guard
        registered: List[ObjectEntry] = []

        def try_reply(timed_out: bool = False) -> None:
            with self.lock:
                if done.is_set():
                    return
                missing = [o for o in oids if not self._object_ready(o)]
                if missing and not timed_out:
                    return
                done.set()
                _unregister_waiter(registered, try_reply)
                results = {}
                for o in oids:
                    e = self.objects.get(o)
                    if e is None or e.state == PENDING:
                        results[o] = ("missing", None, 0)
                    else:
                        results[o] = (e.loc if e.state == READY else "error",
                                      e.data, e.size)
                ctx.reply(m, {"results": results,
                              "timed_out": bool(missing)})

        with self.lock:
            missing = [o for o in oids if not self._object_ready(o)]
            for o in missing:
                entry = self.objects.get(o)
                if entry is None:
                    entry = ObjectEntry()
                    # get for an unknown object: wait for someone to put it
                    entry.refcount = 0
                    entry.foreign = True
                    self.objects[o] = entry
                entry.waiters.append(try_reply)
                registered.append(entry)
                self._ensure_pull(o)
            if timeout == 0:
                try_reply(timed_out=True)
                return
            if deadline is not None and missing:
                self._add_deadline_waiter(
                    deadline, lambda: try_reply(timed_out=True))
        try_reply()

    def _h_wait(self, ctx: _ConnCtx, m: dict) -> None:
        oids: List[bytes] = m["object_ids"]
        num_returns: int = m["num_returns"]
        timeout = m.get("timeout")
        deadline = time.time() + timeout if timeout is not None else None
        done = threading.Event()
        registered: List[ObjectEntry] = []

        def try_reply(timed_out: bool = False) -> None:
            with self.lock:
                if done.is_set():
                    return
                ready = [o for o in oids if self._object_ready(o)]
                if len(ready) < num_returns and not timed_out:
                    return
                done.set()
                _unregister_waiter(registered, try_reply)
                satisfied = len(ready) >= num_returns
                if satisfied:
                    ready = ready[:num_returns]
                ctx.reply(m, {"ready": ready, "timed_out": not satisfied})

        with self.lock:
            for o in oids:
                if not self._object_ready(o):
                    entry = self.objects.get(o)
                    if entry is None:
                        entry = ObjectEntry()
                        entry.refcount = 0
                        entry.foreign = True
                        self.objects[o] = entry
                    entry.waiters.append(try_reply)
                    registered.append(entry)
                    self._ensure_pull(o)
            if timeout == 0:
                try_reply(timed_out=True)
                return
            if deadline is not None:
                self._add_deadline_waiter(
                    deadline, lambda: try_reply(timed_out=True))
        try_reply()

    def _h_task_started(self, ctx: _ConnCtx, m: dict) -> None:
        """Worker signal: user code for an actor call began executing.
        Until this arrives a dispatched call is still replayable (it
        sat in the worker's queue) — worker death requeues it for free
        instead of burning retry budget or surfacing an error."""
        with self.lock:
            rec = self.tasks.get(m["task_id"])
            if rec is not None:
                rec.started = True
                rec.stages.setdefault("executing", time.time())

    def _h_task_done(self, ctx: _ConnCtx, m: dict) -> None:
        notify_owner: Optional[bytes] = None
        fwd_returns: List[tuple] = []
        prof = m.get("profile")
        if prof is not None:
            prof["node_id"] = self.node_id.hex()
            self._emit_event(prof)
        with self.lock:
            rec = self.tasks.pop(m["task_id"], None)
            if (rec is not None and self.multinode
                    and rec.spec.get("owner_node") not in (None,
                                                           self.node_id)):
                notify_owner = rec.spec["owner_node"]
            w = ctx.worker
            if (m.get("failed") and m.get("app_retryable")
                    and rec is not None and rec.retries_left > 0
                    and not rec.cancelled and rec.actor_id is None):
                # retry_exceptions matched (decided worker-side): the
                # error is NOT registered on the return objects — the
                # task resubmits after backoff, waiters stay parked,
                # and the submitter's embedded holds stay live for the
                # replay.  Returning here also skips forward_done: a
                # forwarded task is only "done" for its owner once a
                # run actually completes.
                self._schedule_retry(
                    rec, "app_error",
                    "application exception matched retry_exceptions")
                if w is not None and w.state == "busy" \
                        and w.actor_id is None:
                    self._release_worker(w)
                self._schedule()
                return
            for oid, loc, data, size, embedded in m["returns"]:
                entry = self.objects.get(oid)
                if entry is not None and entry.deleted:
                    continue
                if rec is not None and rec.cancelled and loc == "error":
                    # Normalize the in-worker KeyboardInterrupt to the
                    # typed cancellation error (reference:
                    # TaskCancelledError on get()).
                    blob = ser.dumps(exc.TaskCancelledError(
                        f"task {rec.spec.get('name')!r} was cancelled"))
                    loc, data, size = "error", blob, len(blob)
                self._register_object(
                    oid, loc, data, size,
                    state=FAILED if loc == "error" else READY,
                    embedded=embedded, creator_pid=ctx.pid,
                    owner=(rec.spec.get("owner")
                           if rec is not None else None))
                if (notify_owner is not None
                        and loc in ("inline", "error")
                        and data is not None):
                    # Piggyback inline/error results on the peer-to-peer
                    # forward_done so the owner registers them without a
                    # GCS location lookup — a forwarded actor call (the
                    # Serve hot path) keeps answering through a full GCS
                    # outage.  shm-sized results still travel via the
                    # location directory + transfer plane.
                    fwd_returns.append((oid, loc, data, size))
                if oid in self._streams:
                    self.finish_stream(oid)   # wake parked consumers
            if rec is not None:
                rec.state = "done"
                self._emit_lifecycle(rec, prof=prof,
                                     failed=m.get("failed", False))
                # Lineage for reconstruction: remember how each return
                # was produced (plain tasks only — actor calls depend on
                # actor state and are not replayable).
                if rec.actor_id is None and not m.get("failed"):
                    for oid in rec.spec["return_ids"]:
                        e = self.objects.get(oid)
                        if e is not None:
                            e.lineage = rec.spec
                # Release the holds the submitter took on arg/embedded
                # refs — EXCEPT for actor creation tasks, whose spec may
                # be replayed on restart (holds released at permanent
                # actor death instead), and EXCEPT for forwarded tasks:
                # the matching increfs live on the OWNER node's entries
                # (released there via forward_done); decref'ing local
                # pulled replicas here would be unbalanced and could
                # free the only copy of an intermediate result.
                foreign_task = rec.spec.get("owner_node") not in (
                    None, self.node_id)
                if not rec.is_actor_creation and not foreign_task:
                    for dep in rec.spec.get("embedded") or []:
                        self._decref(dep)
                if rec.is_actor_creation and rec.actor_id:
                    self._on_actor_created(rec, failed=m.get("failed", False))
                actor = self.actors.get(rec.actor_id) if rec.actor_id else None
                if actor is not None:
                    actor.in_flight.pop(rec.task_id, None)
                    self._maybe_release_actor(actor)
            if w is not None and w.state == "busy" and w.actor_id is None:
                self._release_worker(w)
            elif w is not None and w.actor_id is not None:
                w.current_task = None
            self._schedule()
        if notify_owner is not None:
            self._peer_notify(notify_owner,
                              {"type": "forward_done",
                               "task_id": m["task_id"],
                               "returns": fwd_returns})

    def _peer_notify(self, nid: bytes, msg: dict) -> None:
        """One-way message to a peer, reusing that peer's FIFO sender
        when one exists (no thread churn on the task-done hot path)."""
        q = self._fwd_queues.get(nid)
        if q is not None:
            q.put(("notify", msg, None))
            return

        def _send():
            ninfo = self._node_info(nid)
            if ninfo is None:
                return
            try:
                self._peer_conn_to(ninfo).notify(msg)
            except Exception:
                pass

        threading.Thread(target=_send, daemon=True,
                         name="rtpu-peer-notify").start()

    def _h_worker_blocked(self, ctx: _ConnCtx, m: dict) -> None:
        # A worker blocked in get(): return its CPU to the pool so nested
        # tasks can run (reference: worker blocked-on-get lease release).
        with self.lock:
            w = ctx.worker
            if w is not None and w.state == "busy":
                w.state = "blocked"
                self._release_held(w)
                self._schedule()

    def _h_worker_unblocked(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            w = ctx.worker
            if w is not None and w.state == "blocked":
                # Overcommit on purpose: the task must finish.
                b = (self.bundles.get(w.bundle_key)
                     if w.bundle_key else None)
                if b is not None:
                    _charge(b.free, w.resources_held)
                else:
                    self._take(w.resources_held, allow_negative=True)
                w.state = "busy"

    def _h_add_ref(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            e = self.objects.get(m["object_id"])
            if e is not None:
                e.refcount += 1
        if "__req_id__" in m:
            ctx.reply(m, {"ok": True})

    def _h_remove_ref(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            self._decref(m["object_id"])

    def _delete_object(self, oid: bytes, e: ObjectEntry) -> None:
        """Caller holds self.lock."""
        e.deleted = True
        e.data = None
        self.objects.pop(oid, None)
        self._obj_loc_cache.pop(oid, None)
        self._drop_spill_fd(oid)
        if e.spill_path:
            try:
                os.unlink(e.spill_path)
            except OSError:
                pass
        if oid in self._pulls_inflight:
            self._cancelled_pulls.add(oid)
        if self.multinode and e.foreign and e.loc == "shm":
            # Freed a pulled replica: prune this node from the holder set
            # so peers stop trying to fetch from us (notify — lock-safe).
            try:
                self.gcs.remove_location(oid, self.node_id)
            except Exception:
                pass
        if self.multinode and not e.foreign:
            # Owner-driven global delete: the GCS drops the record and
            # pushes object_deleted to every holder (notify — lock-safe).
            try:
                self.gcs.remove_object(oid)
            except Exception:
                pass
        if e.loc == "shm":
            # Release the creator pin the directory owns, then delete
            # (deferred store-side while readers still hold pins).
            try:
                store = self._store()
                store.release(_OID(oid))
                store.delete(_OID(oid))
            except Exception:
                pass
        # Release refs embedded in this object's payload (may cascade).
        embedded, e.embedded = e.embedded, []
        for dep in embedded:
            self._decref(dep)

    def _decref(self, oid: bytes) -> None:
        """Caller holds self.lock."""
        e = self.objects.get(oid)
        if e is None:
            return
        e.refcount -= 1
        if e.refcount <= 0:
            self._delete_object(oid, e)

    _store_client = None

    def _store(self):
        if NodeService._store_client is None:
            from ray_tpu._private.shm_store import ShmObjectStore
            NodeService._store_client = ShmObjectStore(self.store_path)
        return NodeService._store_client

    # -- GCS passthrough ---------------------------------------------------
    def _h_cancel_task(self, ctx: _ConnCtx, m: dict) -> None:
        """ray_tpu.cancel (reference: ray.cancel / CancelTask RPC):
        pending tasks fail immediately with TaskCancelledError;
        dispatched tasks get SIGINT (KeyboardInterrupt in the worker,
        the reference's in-band cancel) or SIGKILL with force=True.
        Retries never resurrect a cancelled task; actor tasks are
        rejected (only async-actor cancel exists in the reference; our
        actors are in-order queues)."""
        oid = m["object_id"]
        force = m.get("force", False)
        victim = None
        with self.lock:
            rec = None
            e = self.objects.get(oid)
            if e is not None and e.producing_task is not None:
                rec = self.tasks.get(e.producing_task)
            if rec is None:
                for r in list(self.tasks.values()):
                    if oid in r.spec["return_ids"]:
                        rec = r
                        break
            if rec is None or rec.state == "done":
                ctx.reply(m, {"ok": False, "state": "done"})
                return
            if rec.actor_id is not None and not rec.is_actor_creation:
                ctx.reply(m, {"__error__": ValueError(
                    "actor tasks cannot be cancelled")})
                return
            rec.cancelled = True
            rec.retries_left = 0
            if rec.state in ("pending", "retry_backoff"):
                # retry_backoff: the parked resubmission callback
                # checks rec.state and becomes a no-op.
                self._fail_task_returns(rec, exc.TaskCancelledError(
                    f"task {rec.spec.get('name')!r} was cancelled "
                    f"before it started"))
                self._schedule()
                ctx.reply(m, {"ok": True, "state": "pending"})
                return
            victim = rec.worker
        if victim is not None and victim.proc is not None:
            try:
                if force:
                    victim.proc.kill()
                else:
                    import signal
                    os.kill(victim.pid, signal.SIGINT)
            except OSError:
                pass
        ctx.reply(m, {"ok": True, "state": "dispatched"})

    def _gcs_proxy(self, ctx: _ConnCtx, m: dict, fn) -> None:
        """Run a blocking GCS-dependent handler off the conn thread,
        in THIS client's submission order, and reply asynchronously.

        A connection dispatches its client's rpcs serially, and
        GcsClient calls queue through a GCS outage (reconnect with
        backoff, up to gcs_reconnect_max_s): executed inline, one kv
        op during an outage would wedge every later rpc from the same
        client — including task_done from a worker, stalling results
        that never needed the GCS.  Only the CALLER of a GCS-dependent
        op should wait out the outage.  Single-node (embedded state,
        never blocks) stays inline."""
        if not self.multinode:
            try:
                ctx.reply(m, fn())
            except Exception as e:
                ctx.reply(m, {"__error__": e})
            return
        q = ctx.gcs_q
        if q is None:
            q = ctx.gcs_q = queue.Queue()

            def drain(_q=q, _ctx=ctx) -> None:
                while not self._shutdown:
                    try:
                        item = _q.get(timeout=5.0)
                    except queue.Empty:
                        # Reap the drainer once its conn is gone.
                        # Lock-free membership probe: list scans are
                        # GIL-safe and a stale answer only costs one
                        # extra 5s idle loop.
                        if _ctx not in self._conns:  # ray-tpu: noqa[RT010]
                            return
                        continue
                    req, job = item
                    try:
                        out = job()
                    except Exception as e:
                        out = {"__error__": e}
                    try:
                        _ctx.reply(req, out)
                    except Exception:
                        pass

            threading.Thread(target=drain, daemon=True,
                             name="rtpu-gcs-proxy").start()
        q.put((m, fn))

    def _h_kv_put(self, ctx: _ConnCtx, m: dict) -> None:
        self._gcs_proxy(ctx, m, lambda: {"ok": self.gcs.kv_put(
            m["ns"], m["key"], m["value"], m.get("overwrite", True))})

    def _h_kv_get(self, ctx: _ConnCtx, m: dict) -> None:
        self._gcs_proxy(ctx, m, lambda: {
            "value": self.gcs.kv_get(m["ns"], m["key"])})

    def _h_kv_wait(self, ctx: _ConnCtx, m: dict) -> None:
        """Long-poll kv read: parked until the key is put or timeout.
        Replaces 2ms client polling in process collectives (weak-spot
        #4 round 2: >=4ms latency floor per collective op)."""
        from ray_tpu._private.gcs import GlobalControlState
        ns, key = m["ns"], m["key"]
        timeout = m.get("timeout", 60.0)
        if isinstance(self.gcs, GlobalControlState):
            fired = threading.Event()

            def cb(value) -> None:
                if fired.is_set():
                    return
                fired.set()
                try:
                    ctx.reply(m, {"value": value})
                except Exception:
                    pass

            def expire() -> None:
                if fired.is_set():
                    return
                self.gcs.kv_wait_unregister(ns, key, cb_outer)
                cb(None)

            def cb_outer(value) -> None:
                # Mark the parked deadline entry dead so the monitor
                # drops it instead of scanning it for up to `timeout`.
                expire.cancelled = True
                cb(value)

            val = self.gcs.kv_wait_register(ns, key, cb_outer)
            if val is not None:
                ctx.reply(m, {"value": val})
                return

            with self.lock:
                self._add_deadline_waiter(time.time() + timeout, expire)
            return

        # Multinode: park at the GCS service via a side thread (the
        # blocking forward must not stall this connection's dispatch).
        def fwd() -> None:
            try:
                value = self.gcs.kv_wait(ns, key, timeout)
            except Exception:
                value = None
            try:
                ctx.reply(m, {"value": value})
            except Exception:
                pass

        threading.Thread(target=fwd, daemon=True,
                         name="rtpu-kv-wait").start()

    def _request_worker_stacks(self, workers: List[WorkerHandle],
                               timeout: float, cb,
                               samples: int = 0,
                               interval_s: float = 0.02) -> None:
        """Ask `workers` for stack captures; `cb(stacks, folded)` fires
        exactly once — when every reply landed or at `timeout`
        (whatever arrived by then).  One-shot mode returns formatted
        per-pid stacks; sampling mode (samples>0) additionally merges
        folded-stack counts (flamegraph input).  Shared by the
        stack_dump RPC and the stall sentinel's targeted captures."""
        token = os.urandom(8)
        rec = {"stacks": {}, "folded": {}, "pending": set(),
               "cb": cb, "done": False}
        with self.lock:
            for w in workers:
                if w.conn_send is None or w.state == "dead":
                    continue
                msg: Dict[str, Any] = {"type": "dump_stacks",
                                       "token": token}
                if samples:
                    msg["samples"] = int(samples)
                    msg["interval_s"] = float(interval_s)
                try:
                    w.conn_send(msg)
                    rec["pending"].add(w.pid)
                except Exception:
                    pass
            if rec["pending"]:
                self._stack_dumps[token] = rec

                def expire() -> None:
                    with self.lock:
                        r = self._stack_dumps.pop(token, None)
                        if r is None or r["done"]:
                            return
                        r["done"] = True
                    try:
                        cb(r["stacks"], r["folded"])
                    except Exception:
                        pass

                self._add_deadline_waiter(time.time() + timeout, expire)
                return
        try:
            cb({}, {})
        except Exception:
            pass

    def _task_workers_locked(self, task_id_hex: str
                             ) -> List[WorkerHandle]:
        """The worker(s) currently running tasks whose id matches the
        hex prefix (actor calls resolve through the actor's resident
        worker).  Caller holds self.lock."""
        out = []
        for rec in self.tasks.values():
            if not rec.task_id.hex().startswith(task_id_hex):
                continue
            w = rec.worker
            if w is None and rec.actor_id is not None:
                a = self.actors.get(rec.actor_id)
                w = a.worker if a is not None else None
            if w is not None and w.state != "dead":
                out.append(w)
        return out

    def _h_stack_dump(self, ctx: _ConnCtx, m: dict) -> None:
        """On-demand stack profiling (reference: the dashboard
        reporter's py-spy role).  Scopes:
        * default: every live worker on this node;
        * task_id (hex prefix): only the worker(s) executing that task;
        * cluster=True (multinode): fan out to every alive peer and
          merge — the documented "every live worker" behavior.
        samples>0 turns one-shot dumps into low-rate sampling (N
        samples, interval_s apart, per worker) whose merged
        folded-stack counts come back under "folded" (flamegraphs)."""
        timeout = m.get("timeout", 10.0)
        samples = int(m.get("samples") or 0)
        interval_s = float(m.get("interval_s") or 0.02)
        task_id = m.get("task_id")
        want_cluster = bool(m.get("cluster")) and self.multinode
        with self.lock:
            if task_id:
                workers = self._task_workers_locked(task_id)
            else:
                workers = [w for w in self.workers.values()
                           if w.conn_send is not None
                           and w.state != "dead"]
        # Sampling keeps workers capturing for samples*interval — give
        # replies room beyond the nominal timeout.
        wait_s = timeout + (samples * interval_s if samples else 0.0)
        merged = {"stacks": {}, "folded": {}}
        merge_lock = threading.Lock()
        remaining = [2 if want_cluster else 1]

        def merge_part(stacks: dict, folded: dict) -> None:
            with merge_lock:
                merged["stacks"].update(stacks)
                for k, v in folded.items():
                    merged["folded"][k] = merged["folded"].get(k, 0) + v
                remaining[0] -= 1
                if remaining[0] > 0:
                    return
            reply = {"stacks": merged["stacks"]}
            if samples:
                reply["folded"] = merged["folded"]
            ctx.reply(m, reply)

        if want_cluster:
            def fanout() -> None:
                sub: Dict[str, Any] = {"type": "stack_dump",
                                       "cluster": False,
                                       "timeout": timeout}
                if task_id:
                    sub["task_id"] = task_id
                if samples:
                    sub["samples"] = samples
                    sub["interval_s"] = interval_s
                replies, _ = self._fanout_peers(sub,
                                                timeout=wait_s + 5.0)
                stacks: Dict[str, str] = {}
                folded: Dict[str, int] = {}
                for n, rep in replies:
                    # Namespace remote pids: across hosts they collide.
                    tag = n["node_id"].hex()[:12]
                    for pid, text in (rep.get("stacks") or {}).items():
                        stacks[f"{pid}@{tag}"] = text
                    for k, v in (rep.get("folded") or {}).items():
                        folded[k] = folded.get(k, 0) + v
                merge_part(stacks, folded)

            threading.Thread(target=fanout, daemon=True,
                             name="rtpu-stack-fanout").start()

        self._request_worker_stacks(workers, wait_s, merge_part,
                                    samples=samples,
                                    interval_s=interval_s)

    def _h_stacks_reply(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            rec = self._stack_dumps.get(m["token"])
            if rec is None or rec["done"]:
                return
            if m.get("text"):
                rec["stacks"][m["pid"]] = m["text"]
            for k, v in (m.get("folded") or {}).items():
                rec["folded"][k] = rec["folded"].get(k, 0) + v
            rec["pending"].discard(m["pid"])
            if rec["pending"]:
                return
            rec["done"] = True
            self._stack_dumps.pop(m["token"], None)
        try:
            rec["cb"](rec["stacks"], rec["folded"])
        except Exception:
            pass

    def _h_kv_del(self, ctx: _ConnCtx, m: dict) -> None:
        self._gcs_proxy(ctx, m, lambda: {
            "ok": self.gcs.kv_del(m["ns"], m["key"])})

    def _h_kv_keys(self, ctx: _ConnCtx, m: dict) -> None:
        self._gcs_proxy(ctx, m, lambda: {
            "keys": self.gcs.kv_keys(m["ns"], m.get("prefix", b""))})

    def _h_fn_register(self, ctx: _ConnCtx, m: dict) -> None:
        def job():
            self.gcs.register_function(m["function_id"], m["blob"])
            return {"ok": True}
        self._gcs_proxy(ctx, m, job)

    def _h_fn_fetch(self, ctx: _ConnCtx, m: dict) -> None:
        self._gcs_proxy(ctx, m, lambda: {
            "blob": self.gcs.fetch_function(m["function_id"])})

    # -- actors ------------------------------------------------------------
    def _h_create_actor(self, ctx: _ConnCtx, m: dict) -> None:
        spec = m["spec"]
        actor_id = spec["actor_id"]
        pgspec = spec.get("pg")
        if pgspec is not None:
            key = (pgspec["id"], pgspec["bundle"])
            with self.lock:
                bundle_here = key in self.bundles
            if not bundle_here:
                # Await PG readiness + route to the bundle's node on a
                # side thread (never block this conn's dispatch loop).
                threading.Thread(target=self._create_actor_with_pg,
                                 args=(ctx, m), daemon=True,
                                 name="rtpu-pg-actor").start()
                return
        aff = spec.get("affinity")
        if (pgspec is None and aff is not None
                and aff["node_id"] != self.node_id):
            ninfo = (self._cluster_node(aff["node_id"])
                     if self.multinode else None)
            if ninfo is None or (aff.get("soft")
                                 and ninfo.get("state") != "alive"):
                if not aff.get("soft"):
                    ctx.reply(m, {"__error__": exc.NodeAffinityError(
                        f"affinity node {aff['node_id'].hex()[:12]} is "
                        f"not alive (soft=False)")})
                    return
                # Soft affinity to a dead/unknown/DRAINING node: fall
                # back to normal placement (spill targets included) —
                # same semantics as the task path clearing rec
                # affinity.  An actor placed on a departing node would
                # need an immediate second migration.
                spec = dict(spec)
                spec["affinity"] = None
                aff = None
        if self.multinode and pgspec is None:
            # Placement: keep the actor local when this node's totals can
            # ever run it; otherwise forward the whole creation to a peer
            # that can (reference: GCS actor scheduling picks a node).
            res = spec.get("resources") or {}
            with self.lock:
                local_ok = (self._local_totals_satisfy(res)
                            if aff is None
                            or aff["node_id"] == self.node_id
                            or self._cluster_node(aff["node_id"]) is None
                            else False)
                if self.draining and local_ok and (
                        aff is None or aff["node_id"] != self.node_id):
                    # Draining: a brand-new actor would outlive the
                    # node only via a second migration — place it on a
                    # healthy peer up front (the actor-migration phase
                    # only covers actors that exist when it runs).
                    # Hard affinity HERE still creates locally and
                    # rides the grace.
                    local_ok = False
            if not local_ok:
                if aff is not None:
                    target = self._cluster_node(aff["node_id"])
                    if (target is not None
                            and target["node_id"] == self.node_id):
                        # Pinned HERE but can't run yet: wait as pending
                        # like the task path — self-forwarding would
                        # recurse into our own create_actor forever.
                        target = None
                else:
                    target = (self._pick_spill_target(res,
                                                      need_avail=True)
                              or self._pick_spill_target(
                                  res, need_avail=False))
                if target is not None:
                    self._actor_homes[actor_id] = target["node_id"]
                    # Track the creation like any forwarded task so this
                    # node's embedded arg holds are released when the
                    # remote creation completes (forward_done) or its
                    # node dies — otherwise the constructor args leak
                    # here forever.
                    spec = dict(spec)
                    spec["creation_task"] = dict(spec["creation_task"])
                    spec["creation_task"]["owner_node"] = self.node_id
                    crec = TaskRecord(spec["creation_task"])
                    with self.lock:
                        self.forwarded[crec.task_id] = (crec,
                                                        target["node_id"])
                    try:
                        conn = self._peer_conn_to(target)
                        conn.call({"type": "create_actor", "spec": spec},
                                  timeout=30.0)
                        ctx.reply(m, {"ok": True})
                    except Exception as e:
                        self._actor_homes.pop(actor_id, None)
                        with self.lock:
                            self.forwarded.pop(crec.task_id, None)
                        ctx.reply(m, {"__error__": e})
                    return
        # Name reservation happens OUTSIDE the state lock: in multinode
        # mode this is a blocking RPC to the GCS process, and blocking
        # gcs.call() under self.lock can deadlock against GCS pushes.
        if spec.get("name") and (spec.get("pg") is not None
                or self._autoscaler_live()
                or self._infeasible_reason(spec.get("resources")) is None):
            ns = spec.get("namespace", "default")
            ok = self.gcs.register_named_actor(ns, spec["name"], actor_id)
            if not ok and self.gcs.lookup_named_actor(
                    ns, spec["name"]) == actor_id:
                # The SAME actor re-registering its own name: a drain
                # migration replays the creation spec on a new node
                # while the GCS registration survives — idempotent.
                ok = True
            if not ok:
                ctx.reply(m, {"__error__": ValueError(
                    f"actor name {spec['name']!r} already taken")})
                return
        with self.lock:
            # Same autoscaler gating as the task path: a live autoscaler
            # may provision the resource, so the actor waits as demand.
            reason = (None if spec.get("pg") is not None
                      or self._autoscaler_live()
                      else self._infeasible_reason(spec.get("resources")))
            if reason is not None:
                actor = ActorRecord(actor_id, spec)
                self.actors[actor_id] = actor
                rec = TaskRecord(spec["creation_task"])
                self.tasks[rec.task_id] = rec
                for oid in rec.spec["return_ids"]:
                    self.objects.setdefault(oid, ObjectEntry())
                self._fail_task_returns(rec, exc.InfeasibleResourceError(
                    f"actor {spec.get('name') or actor_id.hex()} is "
                    f"infeasible: {reason}"))
                # _fail_task_returns skips embedded decrefs for creation
                # tasks (restart replay); this actor will never restart —
                # _mark_actor_dead releases the holds and drops any
                # reserved name (idempotent for unnamed actors).
                self._mark_actor_dead(actor, f"infeasible: {reason}",
                                      teardown_worker=False)
                ctx.reply(m, {"ok": True})
                return
            actor = ActorRecord(actor_id, spec)
            self.actors[actor_id] = actor
            rec = TaskRecord(spec["creation_task"])
            self.tasks[rec.task_id] = rec
            for oid in rec.spec["return_ids"]:
                e = self.objects.setdefault(oid, ObjectEntry())
                e.producing_task = rec.task_id
            rec.deps = {d for d in rec.deps if not self._object_ready(d)}
            if rec.had_deps and not rec.deps:
                rec.stages.setdefault("deps_fetched", time.time())
            for d in rec.deps:
                self._ensure_pull(d)
            if rec.deps and self.multinode:
                rec.stages.setdefault("pull_wait", time.time())
            self.pending_queue.append(rec)
            self._schedule()
        if self.multinode:
            try:
                self.gcs.set_actor_node(actor_id, self.node_id)
            except Exception:
                pass
        ctx.reply(m, {"ok": True})

    def _on_actor_created(self, rec: TaskRecord, failed: bool) -> None:
        """Caller holds self.lock."""
        actor = self.actors.get(rec.actor_id)
        if actor is None:
            return
        if actor.state == "dead":
            # kill() raced creation: do not resurrect — tear the worker
            # down instead of letting a killed actor serve calls.
            if rec.worker is not None:
                self._teardown_worker(rec.worker)
            return
        if failed:
            # Worker death runs through _handle_worker_death (it owns
            # retry/requeue bookkeeping a plain teardown skips).
            self._mark_actor_dead(actor, "creation task failed",
                                  teardown_worker=False)
            if actor.worker is not None:
                self._handle_worker_death(actor.worker, "creation failed",
                                          actor_already_handled=True)
            return
        actor.state = "alive"
        actor.worker = rec.worker
        if rec.worker is not None:
            rec.worker.actor_id = actor.actor_id
            rec.worker.current_task = None
        self._drain_actor_queue(actor)
        # A handle-GC release that arrived during creation waited for
        # this moment (releasing earlier would have dropped the
        # creation args before the constructor ran).
        self._maybe_release_actor(actor)

    def _enqueue_actor_task(self, rec: TaskRecord) -> None:
        """Caller holds self.lock."""
        actor = self.actors.get(rec.actor_id)
        if actor is None and self.multinode:
            # A call routed here on a stale home hint after the actor
            # migrated off this (draining) node: redirect to its new
            # home instead of failing.  Foreign-owned calls hand BACK
            # to their owner (re-forwarding onward would re-own them
            # to this exiting node, and the owner's node-death sweep
            # would fail or double-run a call executing fine at the
            # new home — same rule as _drain_migrate_one).
            home = self._migrated_actors.get(rec.actor_id)
            ninfo = self._cluster_node(home) if home else None
            if ninfo is not None and ninfo.get("state") == "alive":
                owner = rec.spec.get("owner_node")
                if owner not in (None, self.node_id) \
                        and self._cluster_node(owner) is not None:
                    self.tasks.pop(rec.task_id, None)
                    rec.state = "handed_back"
                    self._peer_notify(owner, {"type": "drain_handback",
                                              "spec": rec.spec,
                                              "from": self.node_id})
                else:
                    self._forward_task(rec, ninfo)
                return
        if actor is None or actor.state == "dead":
            reason = actor.death_reason if actor else "unknown actor"
            self._fail_task_returns(rec, exc.ActorDiedError(
                rec.actor_id.hex(), reason, task_started=False))
            return
        actor.queue.append(rec)
        self._drain_actor_queue(actor)

    def _drain_actor_queue(self, actor: ActorRecord) -> None:
        if actor.state != "alive" or actor.worker is None:
            return
        if actor.hold_queue:
            # Node drain is migrating this actor: no new dispatch —
            # queued calls forward to the new home once in-flight ones
            # finish (node_drain._drain_migrate_one).
            return
        # Head-of-line blocking on unmet deps preserves the sync-actor
        # strict submission-order guarantee (a later no-dep call must not
        # overtake an earlier call waiting on its argument).
        while actor.queue and not actor.queue[0].deps:
            rec = actor.queue.popleft()
            rec.state = "dispatched"
            now = time.time()
            if rec.had_deps:
                rec.stages.setdefault("deps_fetched", now)
            rec.stages["worker_assigned"] = now
            # Fresh attempt (restart replays reuse the rec): re-arm
            # the stall sentinel, drop the stale executing checkpoint.
            rec.stall_reported = False
            rec.stages.pop("executing", None)
            actor.in_flight[rec.task_id] = rec
            actor.worker.conn_send({"type": "execute_task",
                                    "spec": rec.spec})
            self._chaos_kill_dispatch(actor.worker)

    def _release_actor_holds(self, actor: ActorRecord) -> None:
        """Release the creation-task embedded ref holds exactly once, at
        permanent actor death (they must outlive restarts: the creation
        spec and its arg blob are replayed)."""
        if actor.holds_released:
            return
        actor.holds_released = True
        for dep in actor.spec["creation_task"].get("embedded") or []:
            self._decref(dep)

    def _fail_actor_queue(self, actor: ActorRecord) -> None:
        # task_started distinguishes queued (never ran — safe for a
        # caller to retry elsewhere, e.g. Serve failover) from
        # in-flight calls (a retry could double side effects).
        while actor.queue:
            self._fail_task_returns(
                actor.queue.popleft(),
                exc.ActorDiedError(actor.actor_id.hex(),
                                   actor.death_reason,
                                   task_started=False))
        for rec in list(actor.in_flight.values()):
            self._fail_task_returns(
                rec, exc.ActorDiedError(actor.actor_id.hex(),
                                        actor.death_reason,
                                        task_started=rec.started))
        actor.in_flight.clear()

    def _h_actor_release_scope(self, ctx: _ConnCtx, m: dict) -> None:
        """Driver GC: the last in-scope handle to a non-detached,
        unnamed actor was collected.  The actor dies once its queued
        and in-flight work drains (reference: actor handle reference
        counting — out-of-scope actors terminate after pending tasks
        complete)."""
        with self.lock:
            actor = self.actors.get(m["actor_id"])
        if actor is None and self.multinode:
            # The actor lives on its home node: one-way forward (the
            # handler never replies, so a call would park a dispatch
            # thread until timeout).
            home = self._actor_homes.get(m["actor_id"])
            if home is None:
                try:
                    home = self.gcs.get_actor_node(m["actor_id"])
                except Exception:
                    home = None
            if home is not None and home != self.node_id:
                self._peer_notify(home, {
                    "type": "actor_release_scope",
                    "actor_id": m["actor_id"]})
            return
        with self.lock:
            actor = self.actors.get(m["actor_id"])
            if actor is None or actor.state == "dead":
                return
            actor.release_on_drain = True
            actor.restarts_left = 0
            self._maybe_release_actor(actor)

    def _mark_actor_dead(self, actor: ActorRecord, reason: str,
                         teardown_worker: bool = True) -> None:
        """Caller holds the lock: THE actor-death bookkeeping sequence
        (state flip, name drop, hold release, queue failure, worker
        teardown) — every death path funnels here so the steps can
        never diverge by cause of death."""
        actor.state = "dead"
        actor.death_reason = reason
        try:
            self.gcs.drop_named_actor(actor.actor_id)
        except Exception:
            # Best-effort cleanup: at shutdown the GCS connection may
            # already be closed when a worker disconnect lands here.
            pass
        self._release_actor_holds(actor)
        self._fail_actor_queue(actor)
        if teardown_worker and actor.worker is not None:
            self._teardown_worker(actor.worker)

    def _maybe_release_actor(self, actor: ActorRecord) -> None:
        """Caller holds the lock: tear the actor down if its release
        was requested and no work remains.  Only a LIVE actor is
        eligible — a pending/restarting actor's creation task rides
        the node's pending_queue (not actor.in_flight), and releasing
        then would decref the creation args before the constructor
        ever ran; _on_actor_created re-checks once alive."""
        if not actor.release_on_drain or actor.state != "alive":
            return
        if actor.in_flight or actor.queue:
            return
        self._mark_actor_dead(actor, "all handles out of scope")

    def _h_actor_exiting(self, ctx: _ConnCtx, m: dict) -> None:
        """Worker announces an INTENTIONAL exit (ray_tpu.exit_actor())
        before its process dies: zero the restart budget so the
        imminent worker death is permanent, and record the reason so
        callers see 'exited' rather than a crash (reference:
        ray.actor.exit_actor semantics)."""
        with self.lock:
            actor = self.actors.get(m["actor_id"])
            if actor is not None and actor.state != "dead":
                actor.restarts_left = 0
                actor.intentional_exit = True
                actor.death_reason = "exited via exit_actor()"

    def _h_kill_actor(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            actor = self.actors.get(m["actor_id"])
        if actor is None and self.multinode:
            fwd = self._forward_actor_rpc(m["actor_id"], {
                "type": "kill_actor", "actor_id": m["actor_id"],
                "no_restart": m.get("no_restart", True)})
            if fwd is not None:
                if m.get("no_restart", True):
                    # A restartable kill leaves the actor alive on its
                    # home node — no tombstone.
                    with self.lock:
                        self._remote_actor_tombstones[m["actor_id"]] = \
                            "killed via kill()"
                ctx.reply(m, fwd)
                return
        with self.lock:
            actor = self.actors.get(m["actor_id"])
            if actor is None:
                ctx.reply(m, {"ok": False})
                return
            if m.get("no_restart", True):
                actor.restarts_left = 0
            self._mark_actor_dead(actor, "killed via kill()")
        ctx.reply(m, {"ok": True})

    def _forward_actor_rpc(self, actor_id: bytes,
                           msg: dict) -> Optional[dict]:
        """Call an actor RPC on the actor's home node; None if the home
        is unknown/unreachable.  Never called under self.lock."""
        home = self._actor_homes.get(actor_id)
        if home is None:
            try:
                home = self.gcs.get_actor_node(actor_id)
            except Exception:
                home = None
        if home is None or home == self.node_id:
            return None
        ninfo = self._node_info(home)
        if ninfo is None:
            return None
        try:
            conn = self._peer_conn_to(ninfo)
            return conn.call(dict(msg), timeout=30.0)
        except Exception:
            return None

    def _h_actor_state(self, ctx: _ConnCtx, m: dict) -> None:
        with self.lock:
            a = self.actors.get(m["actor_id"])
            if a is not None:
                ctx.reply(m, {"state": a.state, "reason": a.death_reason})
                return
            tomb = self._remote_actor_tombstones.get(m["actor_id"])
        if tomb is not None:
            ctx.reply(m, {"state": "dead", "reason": tomb})
            return
        if self.multinode:
            fwd = self._forward_actor_rpc(m["actor_id"], {
                "type": "actor_state", "actor_id": m["actor_id"]})
            if fwd is not None:
                ctx.reply(m, {"state": fwd["state"],
                              "reason": fwd["reason"]})
                return
        ctx.reply(m, {"state": "unknown", "reason": ""})

    def _h_lookup_named_actor(self, ctx: _ConnCtx, m: dict) -> None:
        def job():
            aid = self.gcs.lookup_named_actor(m["namespace"], m["name"])
            spec = None
            with self.lock:
                if aid is not None and aid in self.actors:
                    spec = {k: v for k, v in self.actors[aid].spec.items()
                            if k != "creation_task"}
            if spec is None and aid is not None and self.multinode:
                fwd = self._forward_actor_rpc(aid, {"type": "actor_spec",
                                                    "actor_id": aid})
                if fwd is not None:
                    spec = fwd.get("spec")
            return {"actor_id": aid, "spec": spec}
        self._gcs_proxy(ctx, m, job)

    def _h_list_named_actors(self, ctx: _ConnCtx, m: dict) -> None:
        self._gcs_proxy(ctx, m, lambda: {
            "names": self.gcs.list_named_actors(m.get("namespace"))})

    # -- cluster info ------------------------------------------------------
    def _h_cluster_resources(self, ctx: _ConnCtx, m: dict) -> None:
        if self.multinode:
            try:
                # Bounded: a conn thread serves every rpc from its
                # client serially — a GCS outage must degrade this to
                # the cached cluster view, not park the connection
                # (and everything queued behind it) for the wait.
                self._cluster_view = self.gcs.nodes(max_wait_s=2.0)
            except Exception:
                pass
            total: Dict[str, float] = {}
            avail: Dict[str, float] = {}
            with self.lock:
                mine_t = dict(self.resources_total)
                mine_a = dict(self.resources_avail)
            for n in self._cluster_view:
                src_t = (mine_t if n["node_id"] == self.node_id
                         else n["resources_total"])
                src_a = (mine_a if n["node_id"] == self.node_id
                         else n["resources_avail"])
                for k, v in src_t.items():
                    total[k] = total.get(k, 0.0) + v
                for k, v in src_a.items():
                    avail[k] = avail.get(k, 0.0) + v
            ctx.reply(m, {"total": total, "available": avail,
                          "nodes": self._cluster_view})
            return
        with self.lock:
            ctx.reply(m, {"total": dict(self.resources_total),
                          "available": dict(self.resources_avail)})

    def _h_store_stats(self, ctx: _ConnCtx, m: dict) -> None:
        ctx.reply(m, {"stats": self._store().stats()})

    def _h_node_info(self, ctx: _ConnCtx, m: dict) -> None:
        ctx.reply(m, {"node_id": self.node_id,
                      "session_dir": self.session_dir,
                      "multinode": self.multinode,
                      "gcs_address": self.gcs_address,
                      "host": getattr(self, "host", "127.0.0.1"),
                      "control_port": self.control_port})

    # ------------------------------------------------------------------
    # observability: state dump + metrics (reference: util/state/api.py,
    # _private/metrics_agent.py)
    # ------------------------------------------------------------------
    def _actor_pinned_oids_locked(self) -> set:
        """Objects a live actor on this node holds: creation-spec
        embedded refs (held across restarts) plus the arg/embedded refs
        of its queued and in-flight calls.  Feeds the pinned_by_actor
        reference kind of the memory plane.  Caller holds self.lock."""
        pinned: set = set()
        for a in self.actors.values():
            if a.state == "dead":
                continue
            ct = a.spec.get("creation_task") or {}
            pinned.update(ct.get("embedded") or [])
            for rec in list(a.queue) + list(a.in_flight.values()):
                for arg in rec.spec.get("args") or []:
                    if arg and arg[0] == "ref":
                        pinned.add(arg[1])
                pinned.update(rec.spec.get("embedded") or [])
        return pinned

    def _memory_kind_bytes_locked(self) -> Dict[str, Dict[str, float]]:
        """Per-reference-kind {bytes, count} over this node's READY
        object directory — the ray_tpu_object_store_bytes{kind} gauge
        source.  Cached for a few seconds: the walk is O(objects +
        actor queues) under the lock, and scrapes arrive on a clock.
        Caller holds self.lock."""
        ts, cached = self._mem_kind_cache
        now = time.time()
        if now - ts < 5.0:
            return cached
        pinned = self._actor_pinned_oids_locked()
        out: Dict[str, Dict[str, float]] = {}
        for oid, e in self.objects.items():
            if e.state != READY:
                continue
            kind = _reference_kind(e, oid in pinned)
            cell = out.setdefault(kind, {"bytes": 0.0, "count": 0.0})
            cell["bytes"] += float(e.size or 0)
            cell["count"] += 1.0
        self._mem_kind_cache = (now, out)
        return out

    def _local_state_dump(self) -> dict:
        """Snapshot of this node's runtime state.  Caller must NOT hold
        the lock."""
        with self.lock:
            tasks = []
            for rec in self.tasks.values():
                tasks.append({
                    "task_id": rec.task_id.hex(),
                    "name": rec.spec.get("name", ""),
                    "state": rec.state,
                    "actor_id": (rec.actor_id.hex()
                                 if rec.actor_id else None),
                    "is_actor_creation": rec.is_actor_creation,
                    "retries_left": rec.retries_left,
                    "pid": rec.worker.pid if rec.worker else None,
                    "node_id": self.node_id.hex(),
                })
            actors = []
            for a in self.actors.values():
                actors.append({
                    "actor_id": a.actor_id.hex(),
                    "name": a.name,
                    "namespace": a.namespace,
                    "class_name": (a.spec.get("class_name")
                                   or a.spec.get("creation_task", {})
                                   .get("name", "").removesuffix(
                                       ".__init__")),
                    "state": a.state,
                    "pid": a.worker.pid if a.worker else None,
                    "restarts_left": a.restarts_left,
                    "detached": a.detached,
                    "queued": len(a.queue),
                    "in_flight": len(a.in_flight),
                    "death_reason": a.death_reason,
                    "node_id": self.node_id.hex(),
                })
            workers = []
            for w in self.workers.values():
                workers.append({
                    "worker_id": w.worker_id.hex(),
                    "pid": w.pid,
                    "state": w.state,
                    "tpu": w.tpu,
                    "task": (w.current_task.spec.get("name")
                             if w.current_task else None),
                    "actor_id": (w.actor_id.hex()
                                 if w.actor_id else None),
                    "node_id": self.node_id.hex(),
                })
            objects = []
            pinned = self._actor_pinned_oids_locked()
            now = time.time()
            my_hex = self.node_id.hex()
            for oid, e in self.objects.items():
                kind = _reference_kind(e, oid in pinned)
                objects.append({
                    "object_id": oid.hex(),
                    "state": ("failed" if e.state == FAILED else
                              "ready" if e.state == READY else "pending"),
                    "loc": e.loc,
                    "size": e.size,
                    "size_bytes": e.size,
                    "refcount": e.refcount,
                    "foreign": e.foreign,
                    "reference_kind": kind,
                    "owner": e.owner.hex() if e.owner else None,
                    "age_s": round(now - e.created_ts, 3),
                    "created_ts": e.created_ts,
                    # Local view; the cluster merge in _h_state_dump
                    # rebuilds this across every node's copies.
                    "holder_nodes": ([my_hex] if e.state == READY
                                     and e.loc in ("inline", "shm",
                                                   "spilled") else []),
                    "has_lineage": e.lineage is not None,
                    "node_id": my_hex,
                })
            # Live client ids (driver + workers): memory_summary uses
            # this to flag owned objects whose owner process is gone.
            clients = {w.worker_id.hex() for w in self.workers.values()
                       if w.state != "dead"}
            for c in self._conns:
                if c.client_id is not None:
                    clients.add(c.client_id.hex())
            pgs = []
            for pgid, pg in self.pgs.items():
                pgs.append({
                    "pg_id": pgid.hex(),
                    "name": pg.get("name"),
                    "strategy": pg.get("strategy"),
                    "state": pg.get("state"),
                    "bundles": pg.get("bundles"),
                    "node_id": self.node_id.hex(),
                })
            pending = len(self.pending_queue)
            sched = self._sched_summary_locked()
        store = self._store().stats()
        return {"tasks": tasks, "actors": actors, "workers": workers,
                "objects": objects, "placement_groups": pgs,
                "clients": sorted(clients),
                "node_id": self.node_id.hex(),
                "pending_tasks": pending,
                "store": store,
                "stores": {self.node_id.hex(): store},
                "dag_channel_items": {
                    self.node_id.hex(): dict(self._dag_items)},
                "scheduling": {
                    self.node_id.hex(): sched}}

    def _fanout_peers(self, request: dict, timeout: float = 2.0
                      ) -> Tuple[List[Tuple[dict, dict]], List[str]]:
        """Issue one RPC to every alive peer IN PARALLEL; returns
        ([(node_info, reply)...], [unreachable node id hexes]).  Serial
        per-peer timeouts would stack past the caller's deadline on big
        clusters."""
        from concurrent.futures import ThreadPoolExecutor

        # Draining nodes are still reachable and still hold state worth
        # observing (their tasks/objects appear in dumps until they go).
        peers = [n for n in self._cluster_view
                 if n["node_id"] != self.node_id
                 and n.get("state") in ("alive", "draining")]
        if not peers:
            return [], []
        results: List[Tuple[dict, dict]] = []
        unreachable: List[str] = []

        def one(n):
            try:
                conn = self._peer_conn_to(n)
                return n, conn.call(dict(request), timeout=timeout)
            except Exception:
                return n, None

        with ThreadPoolExecutor(max_workers=min(8, len(peers))) as ex:
            for n, reply in ex.map(one, peers):
                if reply is None:
                    unreachable.append(n["node_id"].hex())
                else:
                    results.append((n, reply))
        return results, unreachable

    def _h_state_dump(self, ctx: _ConnCtx, m: dict) -> None:
        dump = self._local_state_dump()
        if m.get("cluster") and self.multinode:
            merged = {k: list(dump[k]) for k in
                      ("tasks", "actors", "workers", "objects",
                       "placement_groups")}
            replies, unreachable = self._fanout_peers(
                {"type": "state_dump", "cluster": False})
            clients = set(dump.get("clients") or [])
            stores = dict(dump.get("stores") or {})
            dag_items = dict(dump.get("dag_channel_items") or {})
            scheduling = dict(dump.get("scheduling") or {})
            for _, peer in replies:
                for k in merged:
                    merged[k].extend(peer["dump"].get(k, []))
                clients.update(peer["dump"].get("clients") or [])
                stores.update(peer["dump"].get("stores") or {})
                dag_items.update(
                    peer["dump"].get("dag_channel_items") or {})
                scheduling.update(
                    peer["dump"].get("scheduling") or {})
            # Holder sets are a cluster-level fact: rebuild them from
            # every node's local copies so list_objects/memory_summary
            # show where each object's replicas actually live.
            holders: Dict[str, set] = {}
            for row in merged["objects"]:
                for h in row.get("holder_nodes") or []:
                    holders.setdefault(row["object_id"], set()).add(h)
            for row in merged["objects"]:
                row["holder_nodes"] = sorted(
                    holders.get(row["object_id"], ()))
            merged["nodes"] = list(self._cluster_view)
            # Partial snapshots must say so — silently missing nodes
            # send operators debugging the wrong thing.
            merged["unreachable_nodes"] = unreachable
            merged["node_id"] = dump["node_id"]
            merged["pending_tasks"] = dump["pending_tasks"]
            merged["store"] = dump["store"]
            merged["stores"] = stores
            merged["clients"] = sorted(clients)
            merged["dag_channel_items"] = dag_items
            merged["scheduling"] = scheduling
            ctx.reply(m, {"dump": merged})
            return
        ctx.reply(m, {"dump": dump})

    # ------------------------------------------------------------------
    # metrics history ring + doctor probe (control-plane observability)
    # ------------------------------------------------------------------
    def _history_sample_tick(self) -> None:
        """Monitor-loop job: append one (ts, value) sample per tracked
        series to the bounded history rings (counters sample their
        running total, gauges their last value, histograms their
        observation count) plus a few runtime built-ins — the data
        behind state.metric_history() / /api/metrics/history /
        `ray_tpu top`."""
        now = time.time()
        res_s = max(config.metrics_history_resolution_s, 0.05)
        cap = max(int(config.metrics_history_window_s / res_s), 2)
        max_series = config.metrics_history_max_series
        try:
            store_used = float(
                self._store().stats().get("used_bytes", 0))
        except Exception:
            store_used = 0.0
        with self._rpc_lock:
            rpc_counts = [(m, float(st["count"]), float(st["inflight"]))
                          for m, st in self._rpc_stats.items()]
        with self.lock:
            rows = []
            for key, s in self._metrics.items():
                if s["kind"] == "histogram":
                    rows.append((key, float(s.get("count") or 0.0)))
                else:
                    rows.append((key, float(s.get("value") or 0.0)))
            from ray_tpu.util.metrics import (RPC_INFLIGHT_METRIC,
                                              RPC_SERVER_SECONDS_METRIC)
            for method, count, inflight in rpc_counts:
                mt = (("method", method),)
                rows.append(((RPC_SERVER_SECONDS_METRIC, "histogram",
                              mt), count))
                rows.append(((RPC_INFLIGHT_METRIC, "gauge", mt),
                             inflight))
            rows.extend((
                (("ray_tpu_tasks_pending", "gauge", ()),
                 float(len(self.pending_queue))),
                (("ray_tpu_tasks_total", "gauge", ()),
                 float(len(self.tasks))),
                (("ray_tpu_actors_alive", "gauge", ()),
                 float(sum(1 for a in self.actors.values()
                           if a.state == "alive"))),
                (("ray_tpu_workers", "gauge", ()),
                 float(len(self.workers))),
                (("ray_tpu_objects_local", "gauge", ()),
                 float(len(self.objects))),
                (("ray_tpu_object_store_bytes_used", "gauge", ()),
                 store_used),
            ))
            hist = self._metrics_history
            for key, val in rows:
                ring = hist.get(key)
                if ring is None:
                    if len(hist) >= max_series:
                        continue   # cardinality cap: drop new series
                    ring = deque(maxlen=cap)
                    hist[key] = ring
                elif ring.maxlen != cap:
                    # Window/resolution knobs changed at runtime:
                    # re-bound the ring, keeping the newest samples.
                    ring = deque(ring, maxlen=cap)
                    hist[key] = ring
                ring.append((now, val))

    def _h_metric_history(self, ctx: _ConnCtx, m: dict) -> None:
        """Per-series history samples, optionally cluster-merged (each
        row carries its node_id — the merge is a concat, not a sum)."""
        name = m.get("name") or None
        with self.lock:
            series = []
            for (n, kind, tags), ring in self._metrics_history.items():
                if name and n != name:
                    continue
                series.append({
                    "name": n, "kind": kind, "tags": dict(tags),
                    "node_id": self.node_id.hex(),
                    "samples": [[round(ts, 3), v] for ts, v in ring]})
        if m.get("cluster") and self.multinode:
            replies, unreachable = self._fanout_peers(
                {"type": "metric_history", "name": name,
                 "cluster": False})
            for _, peer in replies:
                series.extend(peer.get("series") or [])
            ctx.reply(m, {"series": series,
                          "unreachable_nodes": unreachable})
            return
        ctx.reply(m, {"series": series, "unreachable_nodes": []})

    def _h_health_probe(self, ctx: _ConnCtx, m: dict) -> None:
        """Doctor's per-node health card: GCS liveness age, GCS status
        card, event-ring drops, slow-RPC tallies, scheduler outcome
        counts — fanned out cluster-wide for state.doctor()."""
        from ray_tpu.util.metrics import EVENTS_DROPPED_METRIC
        now = time.time()
        with self.lock:
            cell = self._metrics.get(
                (EVENTS_DROPPED_METRIC, "counter", ()))
            info = {
                "node_id": self.node_id.hex(),
                "multinode": self.multinode,
                "gcs_last_ok_age_s": round(now - self._gcs_last_ok, 3),
                "gcs_status": dict(self._gcs_status or {}),
                "events_dropped": float(cell["value"]) if cell else 0.0,
                "pending_tasks": len(self.pending_queue),
                "workers": len(self.workers),
                "draining": bool(self.draining),
                "sched_outcomes": dict(self._sched_outcomes),
            }
        with self._rpc_lock:
            info["slow_rpcs"] = {meth: st["slow"]
                                 for meth, st in self._rpc_stats.items()
                                 if st["slow"]}
        if m.get("cluster") and self.multinode:
            replies, unreachable = self._fanout_peers(
                {"type": "health_probe", "cluster": False})
            nodes = [info] + [r.get("info") for _, r in replies
                              if r.get("info")]
            ctx.reply(m, {"info": info, "nodes": nodes,
                          "unreachable_nodes": unreachable})
            return
        ctx.reply(m, {"info": info, "nodes": [info],
                      "unreachable_nodes": []})

    # ------------------------------------------------------------------
    # task-lifecycle tracing (reference: task events + state-API task
    # summaries; chrome-trace via ray.timeline)
    # ------------------------------------------------------------------
    def _emit_event(self, ev: dict) -> None:
        """Append one event to the bounded per-node ring, counting the
        eviction the append forces when the ring is full — a silently
        rolling ring hides lifecycle history from summarize_tasks()
        and the timeline.  Safe with or without self.lock held (RLock)."""
        from ray_tpu.util.metrics import EVENTS_DROPPED_METRIC
        with self.lock:
            if (self._events.maxlen is not None
                    and len(self._events) >= self._events.maxlen):
                self._inc_counter(
                    EVENTS_DROPPED_METRIC, {},
                    "lifecycle/profile events evicted from the "
                    "bounded per-node event ring")
            self._events.append(ev)

    def _emit_lifecycle(self, rec: TaskRecord, prof: Optional[dict],
                        failed: bool) -> None:
        """Record the task's stage-transition record into the event
        ring and fold stage durations into the per-stage histograms.
        Caller holds self.lock."""
        from ray_tpu._private import tracing
        st = dict(rec.stages)
        now = time.time()
        if prof is not None:
            st.setdefault("executing", prof["start"])
            st["finished"] = prof["end"]
        else:
            st.setdefault("finished", now)
        base = rec.spec.get("name") or "<task>"
        tc = rec.spec.get("trace_ctx") or {}
        # Actor dispatch never sets rec.worker (the call rides the
        # actor's resident worker) — resolve the pid through the actor
        # record so the timeline row matches the execute span's.
        pid = rec.worker.pid if rec.worker else 0
        if not pid and rec.actor_id is not None:
            actor = self.actors.get(rec.actor_id)
            if actor is not None and actor.worker is not None:
                pid = actor.worker.pid
        ev = {
            "kind": "lifecycle",
            # ":lifecycle" suffix keeps the record distinct from the
            # worker's execute span of the same task name.
            "name": base + ":lifecycle",
            "task_name": base,
            "task_id": rec.task_id.hex(),
            "trace_id": tracing.task_trace_id(rec.spec),
            "span_id": tracing.lifecycle_span_id(rec.task_id),
            "parent_span_id": tc.get("parent_span_id"),
            "start": st.get("submitted", now),
            "end": st["finished"],
            "stages": st,
            "failed": failed,
            "actor": rec.actor_id is not None,
            "pid": pid,
            "node_id": self.node_id.hex(),
        }
        self._emit_event(ev)
        self._observe_stage_metrics(st)

    def _observe_stage_metrics(self, stages: Dict[str, float]) -> None:
        """Fold one task's stage durations into the auto-registered
        per-stage histograms (ray_tpu_task_stage_duration_seconds,
        declared in util/metrics.py) so a Prometheus scrape exposes
        scheduling delay and queue wait without any user code.  Merged
        directly into the node's aggregate table — same cell layout as
        _h_metrics_push.  Caller holds self.lock."""
        from ray_tpu._private.tracing import stage_durations
        from ray_tpu.util.metrics import (TASK_STAGE_BUCKETS,
                                          TASK_STAGE_METRIC)
        for stage, dur in stage_durations(stages).items():
            self._observe_hist(TASK_STAGE_METRIC, {"stage": stage},
                               dur, TASK_STAGE_BUCKETS,
                               "task lifecycle stage duration")

    def _observe_hist(self, name: str, tags: Dict[str, str],
                      value: float, buckets, description: str = ""
                      ) -> None:
        """Fold one observation into a node-side auto-registered
        histogram cell (same table as _h_metrics_push).  Prefills every
        boundary (like Histogram._new_cell) so each scrape exposes a
        stable, uniform bucket set.  Caller holds self.lock."""
        key = (name, "histogram", tuple(sorted(tags.items())))
        cur = self._metrics.get(key)
        if cur is None:
            cur = {"name": name, "kind": "histogram",
                   "tags": dict(tags), "value": 0.0,
                   "buckets": {str(b): 0 for b in buckets},
                   "sum": 0.0, "count": 0.0,
                   "description": description}
            self._metrics[key] = cur
        for b in buckets:
            if value <= b:
                k = str(b)
                cur["buckets"][k] = cur["buckets"].get(k, 0) + 1
                break
        cur["sum"] += value
        cur["count"] += 1

    def _inc_counter(self, name: str, tags: Dict[str, str],
                     description: str = "",
                     value: float = 1.0) -> None:
        """Bump a node-side auto-registered counter cell (same table
        the stage histograms land in).  Caller holds self.lock."""
        key = (name, "counter", tuple(sorted(tags.items())))
        cur = self._metrics.get(key)
        if cur is None:
            cur = {"name": name, "kind": "counter", "tags": dict(tags),
                   "value": 0.0, "buckets": {}, "sum": 0.0,
                   "count": 0.0, "description": description}
            self._metrics[key] = cur
        cur["value"] += value

    # ------------------------------------------------------------------
    # retry scheduling: exponential backoff with jitter
    # (reference role: task resubmit backoff; the jitter stream is
    # seeded alongside the chaos RNG so a chaos schedule replays)
    # ------------------------------------------------------------------
    def _retry_delay_s(self, rec: TaskRecord) -> float:
        base = max(config.task_retry_delay_ms, 0) / 1000.0
        cap = max(config.task_retry_max_delay_ms, 0) / 1000.0
        attempt = max(rec.spec.get("retries", 0) - rec.retries_left, 1)
        delay = min(cap, base * (2 ** (attempt - 1)))
        # Full-ish jitter in [0.5x, 1x]: staggers a thundering herd of
        # simultaneous retries without ever *extending* the cap.
        return delay * (0.5 + 0.5 * chaos.jitter())

    def _schedule_retry(self, rec: TaskRecord, reason_tag: str,
                        reason: str) -> None:
        """Re-run `rec` after an exponential-backoff delay.  Decrements
        the retry budget, emits the retry lifecycle event + counter,
        and parks the resubmission on the monitor's deadline list.
        Caller holds self.lock and has already verified
        rec.retries_left > 0."""
        rec.retries_left -= 1
        rec.state = "retry_backoff"
        rec.worker = None
        rec.locality_deadline = None
        rec.spec.pop("spilled", None)
        self.tasks[rec.task_id] = rec
        delay = self._retry_delay_s(rec)
        now = time.time()
        self._emit_retry(rec, reason_tag, reason, delay)

        def fire() -> None:
            with self.lock:
                if rec.state != "retry_backoff" or self._shutdown:
                    return      # cancelled / failed during backoff
                rec.state = "pending"
                rec.stages["queued"] = time.time()
                self.pending_queue.append(rec)
                self._schedule()

        self._add_deadline_waiter(now + delay, fire)

    def _requeue_as_reconstruction(self, rec: TaskRecord,
                                   reason: str) -> bool:
        """Re-run a forwarded plain task lost to a node death under the
        object-reconstruction budget.  Caller holds self.lock; returns
        False when the budget is spent (caller fails the returns)."""
        if rec.is_actor_creation or rec.cancelled:
            return False
        entries = []
        for oid in rec.spec["return_ids"]:
            e = self.objects.setdefault(oid, ObjectEntry())
            if e.reconstructions >= config.max_object_reconstructions:
                return False
            entries.append((oid, e))
        for oid, e in entries:
            e.reconstructions += 1
            e.state = PENDING
            e.loc = None
            e.data = None
            e.producing_task = rec.task_id
        rec.state = "pending"
        rec.worker = None
        rec.spec.pop("spilled", None)
        rec.deps = {a[1] for a in rec.spec["args"] if a[0] == "ref"
                    and not self._object_ready(a[1])}
        for d in rec.deps:
            self._ensure_pull(d)
        self.tasks[rec.task_id] = rec
        self.pending_queue.append(rec)
        self._emit_retry(rec, "node_death",
                         f"reconstructing results lost with node: "
                         f"{reason}", 0.0)
        return True

    def _emit_retry(self, rec: TaskRecord, reason_tag: str,
                    reason: str, delay_s: float) -> None:
        """Retry observability, shared by every retry path: the
        counter cell plus one lifecycle event carrying the backoff
        delay and reason.  Caller holds self.lock and has already
        decremented the budget."""
        from ray_tpu.util.metrics import TASK_RETRIES_METRIC
        self._inc_counter(
            TASK_RETRIES_METRIC, {"reason": reason_tag},
            "task retries, by failure reason")
        now = time.time()
        self._emit_event({
            "kind": "retry",
            "name": (rec.spec.get("name") or "<task>") + ":retry",
            "task_id": rec.task_id.hex(),
            "reason": reason,
            "reason_tag": reason_tag,
            "delay_s": delay_s,
            "attempt": rec.spec.get("retries", 0) - rec.retries_left,
            "start": now, "end": now,
            "pid": 0,
            "node_id": self.node_id.hex(),
        })

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _take(self, res: Dict[str, float], allow_negative: bool = False) -> bool:
        for k, v in res.items():
            if not allow_negative and self.resources_avail.get(k, 0.0) < v - 1e-9:
                return False
        for k, v in res.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0.0) - v
        return True

    def _give_back(self, res: Dict[str, float]) -> None:
        for k, v in res.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0.0) + v

    def _schedule_reap(self, w: WorkerHandle) -> None:
        """Reclaim a dead worker's shm pins (read pins + unadopted
        creator pins) — but only once its PROCESS is actually gone:
        reaping a live process (connection lost, SIGTERM still in
        flight) would release pins it is still using.  Caller holds the
        lock."""
        # Chip leases come back immediately: both death paths funnel
        # here, and a replacement TPU worker may spawn this tick.
        self._chip_alloc.release(w.worker_id)
        if not w.pid:
            return
        if w.proc is not None and w.proc.poll() is None:
            self._pending_reaps.append((w.proc, w.pid,
                                        time.time() + 2.0))
            return
        try:
            self._store().reap_client(w.pid)
        except Exception:
            pass

    def _teardown_worker(self, w: WorkerHandle) -> None:
        """Forcibly stop a worker (kill_actor / kill-race paths).
        Caller holds the lock."""
        if w.state == "dead":
            return
        w.state = "dead"
        self._release_held(w)
        w.resources_held = {}
        w.bundle_key = None
        if w.conn_send:
            try:
                w.conn_send({"type": "exit"})
            except Exception:
                pass
        if w.proc is not None:
            w.proc.terminate()
        self.workers.pop(w.worker_id, None)
        self._schedule_reap(w)

    def _release_worker(self, w: WorkerHandle) -> None:
        self._release_held(w)
        w.resources_held = {}
        w.bundle_key = None
        w.current_task = None
        w.state = "idle"
        w.last_idle_time = time.time()

    def _cluster_node(self, nid: bytes) -> Optional[dict]:
        """_cluster_view lookup WITHOUT any GCS round-trip (lock-safe)."""
        for n in self._cluster_view:
            if n["node_id"] == nid:
                return n
        return None

    def _sched_note(self, rec: TaskRecord, outcome: str,
                    **detail) -> None:
        """Record one scheduler placement decision: outcome counter,
        placement-latency histogram (terminal outcomes), the bounded
        recent-decision ring behind state.summarize_scheduling(), and
        the rate-limited `sched.decide` span accumulator.  Caller
        holds self.lock.  Non-terminal outcomes (queue /
        drain_handback) count once per queue episode, not once per
        scheduling pass — _schedule revisits the queue on every
        resource change."""
        from ray_tpu.util.metrics import (SCHED_DECISIONS_METRIC,
                                          SCHED_PLACEMENT_BUCKETS,
                                          SCHED_PLACEMENT_SECONDS_METRIC)
        terminal = outcome in ("local", "forward", "spill",
                               "infeasible")
        if not terminal:
            if rec.task_id in self._sched_noted:
                return
            if len(self._sched_noted) > 100_000:
                # Cancelled-while-queued strays: intersect with live
                # tasks instead of growing forever.
                self._sched_noted &= set(self.tasks)
            self._sched_noted.add(rec.task_id)
        else:
            self._sched_noted.discard(rec.task_id)
        self._inc_counter(SCHED_DECISIONS_METRIC, {"outcome": outcome},
                          "scheduler placement decisions by outcome")
        self._sched_outcomes[outcome] = \
            self._sched_outcomes.get(outcome, 0) + 1
        if outcome in ("local", "forward", "spill"):
            t0 = rec.stages.get("submitted")
            if t0 is not None:
                self._observe_hist(
                    SCHED_PLACEMENT_SECONDS_METRIC,
                    {"outcome": outcome}, time.time() - t0,
                    SCHED_PLACEMENT_BUCKETS,
                    "task submit->placement latency by outcome")
        row = {"task": rec.spec.get("name") or "<task>",
               "task_id": rec.task_id.hex()[:16],
               "outcome": outcome, "ts": time.time()}
        row.update(detail)
        self._sched_recent.append(row)
        if not self._sched_span:
            self._sched_span_t0 = time.time()
        self._sched_span[outcome] = \
            self._sched_span.get(outcome, 0) + 1

    def _flush_sched_span_locked(self) -> None:
        """Emit the accumulated decision counts as ONE sampled
        `sched.decide` timeline span, at most once per
        sched_span_min_interval_s (per-decision spans would be the
        PR-8 hot-path trap at 10k placements/s).  Caller holds
        self.lock."""
        if not self._sched_span:
            return
        now = time.time()
        min_iv = config.sched_span_min_interval_s
        if min_iv > 0 and now < self._next_sched_span:
            return
        self._next_sched_span = now + max(min_iv, 0.0)
        counts, self._sched_span = self._sched_span, {}
        self._emit_event({
            "kind": "sched",
            "name": "sched.decide",
            "outcomes": counts,
            "decisions": sum(counts.values()),
            "pid": os.getpid(),
            "start": self._sched_span_t0 or now, "end": now,
            "node_id": self.node_id.hex(),
        })

    def _sched_summary_locked(self) -> dict:
        """This node's scheduler-decision summary (cumulative outcome
        counts + the recent-decision ring).  Caller holds self.lock."""
        return {"outcomes": dict(self._sched_outcomes),
                "pending": len(self.pending_queue),
                "recent": list(self._sched_recent)}

    def _schedule(self) -> None:
        """Dispatch every runnable pending task. Caller holds self.lock."""
        if self._shutdown:
            return
        progressed = True
        while progressed:
            progressed = False
            for rec in list(self.pending_queue):
                if rec.deps:
                    continue
                if (self.draining and self.multinode
                        and rec.actor_id is None
                        and not rec.is_actor_creation
                        and rec.spec.get("pg") is None
                        and not rec.drain_keep):
                    # Draining: no new leases for movable work — the
                    # handback sweep (node_drain) forwards it to a
                    # healthy peer or marks it drain_keep when nothing
                    # can take it (then it runs here within the grace).
                    self._sched_note(rec, "drain_handback")
                    continue
                res = dict(rec.spec.get("resources") or {})
                needs_tpu = res.get("TPU", 0) > 0
                aff = rec.spec.get("affinity")
                if aff is not None and aff["node_id"] != self.node_id:
                    # Node affinity: route to the pinned node; hard
                    # affinity to a dead node fails, soft falls back
                    # (reference: NodeAffinitySchedulingStrategy).
                    # A DRAINING target counts as gone for SOFT
                    # affinity (chasing it would ping-pong with its
                    # handback sweep); hard pins still forward — the
                    # node can run the task within its drain grace.
                    ninfo = (self._cluster_node(aff["node_id"])
                             if self.multinode else None)
                    if ninfo is not None and (
                            ninfo.get("state") == "alive"
                            or not aff.get("soft")):
                        self._forward_task(rec, ninfo)
                        self._sched_note(
                            rec, "forward", reason="affinity",
                            target=ninfo["node_id"].hex()[:12])
                        progressed = True
                        continue
                    if aff.get("soft"):
                        rec.spec["affinity"] = None
                    else:
                        self.pending_queue.remove(rec)
                        self.tasks.pop(rec.task_id, None)
                        self._sched_note(
                            rec, "infeasible", reason="affinity_dead",
                            target=aff["node_id"].hex()[:12])
                        self._fail_task_returns(
                            rec, exc.NodeAffinityError(
                                f"affinity node "
                                f"{aff['node_id'].hex()[:12]} is not "
                                f"alive (soft=False)"))
                        progressed = True
                        continue
                pg = rec.spec.get("pg")
                bundle = None
                key = None
                if pg is not None:
                    key = (pg["id"], pg["bundle"])
                    bundle = self.bundles.get(key)
                    if bundle is None:
                        # Not our bundle: route to its home node (known
                        # once the PG committed); wait while pending.
                        target = self._pg_bundle_node(pg)
                        if (self.multinode and target is not None
                                and target != self.node_id):
                            ninfo = self._cluster_node(target)
                            if ninfo is not None:
                                self._forward_task(rec, ninfo)
                                self._sched_note(
                                    rec, "forward", reason="pg_home",
                                    target=target.hex()[:12])
                                progressed = True
                        continue
                    if not _fits(bundle.free, res):
                        # bundle busy: wait for a pg task end
                        self._sched_note(rec, "queue",
                                         reason="pg_bundle_busy")
                        continue
                    _charge(bundle.free, res)
                elif not self._take(res):
                    # Affinity-pinned work must wait here, not spill.
                    # Streaming generators also stay local: their item
                    # stream lives in THIS node's table, and a peer
                    # executing the task would yield into the wrong one.
                    if (self.multinode
                            and rec.spec.get("affinity") is None
                            and not rec.spec.get("streaming")
                            and self._try_spill(rec, res)):
                        progressed = True
                    else:
                        self._sched_note(rec, "queue",
                                         reason="resources_busy")
                    continue
                from ray_tpu._private.container import image_of
                image = image_of(rec.spec.get("runtime_env"))
                w = self._find_idle_worker(tpu=needs_tpu, image=image)
                if w is None:
                    if bundle is not None:
                        _uncharge(bundle.free, res)
                    else:
                        self._give_back(res)
                    self._maybe_spawn(tpu=needs_tpu, image=image)
                    self._sched_note(rec, "queue",
                                     reason="no_idle_worker")
                    continue
                self.pending_queue.remove(rec)
                rec.state = "dispatched"
                now = time.time()
                if rec.had_deps:
                    rec.stages.setdefault("deps_fetched", now)
                rec.stages["worker_assigned"] = now
                # Fresh execution attempt: re-arm the stall sentinel
                # and drop the dead attempt's executing checkpoint
                # (task_started's setdefault could never refresh it).
                rec.stall_reported = False
                rec.stages.pop("executing", None)
                rec.worker = w
                w.state = "busy"
                w.current_task = rec
                w.resources_held = res
                w.bundle_key = key if bundle is not None else None
                w.conn_send({"type": "execute_task", "spec": rec.spec})
                self._sched_note(rec, "local", worker_pid=w.pid)
                self._chaos_kill_dispatch(w)
                progressed = True
        self._flush_sched_span_locked()

    def _chaos_kill_dispatch(self, w: WorkerHandle) -> None:
        """Chaos kind=kill_worker at site 'dispatch': SIGKILL the worker
        a task was just handed to — the monitor's death sweep then
        drives the crash-retry path.  No-op unless a chaos schedule
        arms it."""
        if not chaos.fire("dispatch", "kill_worker"):
            return
        try:
            if w.proc is not None:
                w.proc.kill()
        except Exception:
            pass

    def _release_held(self, w: WorkerHandle) -> None:
        """Return a worker's held resources to their source pool: the pg
        bundle they came from if it still exists, else the node pool.
        Caller holds self.lock."""
        b = self.bundles.get(w.bundle_key) if w.bundle_key else None
        if b is not None:
            _uncharge(b.free, w.resources_held)
        else:
            self._give_back(w.resources_held)

    def _find_idle_worker(self, tpu: bool,
                          image: Optional[str] = None
                          ) -> Optional[WorkerHandle]:
        """Caller holds self.lock."""
        for w in self.workers.values():
            if (w.state == "idle" and w.tpu == tpu
                    and w.actor_id is None and w.image == image):
                return w
        return None

    def _maybe_spawn(self, tpu: bool,
                     image: Optional[str] = None) -> None:
        """Caller holds self.lock."""
        from ray_tpu._private.container import image_of
        starting = sum(1 for w in self.workers.values()
                       if w.state == "starting" and w.tpu == tpu
                       and w.image == image)
        if self._spawn_failures >= self._spawn_failure_limit:
            return
        demand = sum(
            1 for r in self.pending_queue
            if not r.deps
            and (((r.spec.get("resources") or {}).get("TPU", 0) > 0) == tpu)
            and image_of(r.spec.get("runtime_env")) == image
        ) or 1
        alive = sum(1 for w in self.workers.values() if w.state != "dead")
        want = min(demand - starting, self._max_workers - alive)
        for _ in range(max(want, 0)):
            self._spawn_worker(tpu, image=image)

    def _spawn_worker(self, tpu: bool,
                      image: Optional[str] = None
                      ) -> Optional[WorkerHandle]:
        """Caller holds self.lock."""
        self._next_worker_seq += 1
        worker_id = os.urandom(16)
        env = dict(os.environ)
        if tpu:
            # Lease chip ids so concurrent TPU workers don't fight over
            # the same device (reference: TPU_VISIBLE_CHIPS pinning,
            # accelerators/tpu.py).  An empty lease (more workers than
            # chips) spawns unpinned rather than blocking.
            chips = self._chip_alloc.acquire(worker_id)
            env.update(self._chip_alloc.visible_env(chips))
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_SOCKET"] = self.socket_path
        env["RAY_TPU_STORE_PATH"] = self.store_path
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        # Workers inherit the driver's import environment: the ray_tpu
        # package location plus every driver sys.path entry (so functions
        # pickled by reference from driver-importable modules resolve —
        # the local-cluster behavior the reference gets from its default
        # working_dir runtime env).
        import sys as _sys
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "").split(os.pathsep)
        extra = [pkg_parent] + [p for p in _sys.path
                                if p and os.path.isdir(p)]
        merged = []
        for p in extra + [e for e in existing if e]:
            if p not in merged:
                merged.append(p)
        env["PYTHONPATH"] = os.pathsep.join(merged)
        if not tpu:
            # Plain workers must not grab the TPU chip: jax in a worker
            # sees CPU unless the task explicitly asked for TPU resources.
            env["JAX_PLATFORMS"] = "cpu"
            # Skip TPU-platform plugin registration hooks (e.g. axon's
            # sitecustomize imports jax in every interpreter): CPU workers
            # must start in ~0.3s, not seconds.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        # Capture worker output into a per-worker log file; the tailer
        # thread forwards appended lines to the driver console when
        # config.log_to_driver (reference: worker logs under
        # session/logs/worker-*.out + log monitor tailing).
        log_path = os.path.join(
            self._log_dir,
            f"worker-{self._next_worker_seq:04d}-{worker_id.hex()[:8]}.log")
        log_f = open(log_path, "ab", buffering=0)
        if image is not None:
            # Containerized worker (runtime_env image_uri): same worker
            # program inside the image, session/state paths mounted
            # (reference: _private/runtime_env/image_uri.py).
            from ray_tpu._private import container
            argv = container.build_worker_argv(
                image, env,
                mounts=[self.session_dir,
                        os.path.dirname(self.socket_path),
                        os.path.dirname(self.store_path)])
        else:
            argv = [sys.executable, "-m", "ray_tpu._private.worker_main"]
        try:
            try:
                proc = subprocess.Popen(
                    argv, env=env, cwd=os.getcwd(),
                    stdout=log_f, stderr=subprocess.STDOUT)
            except OSError as e:
                # Missing container runtime / bad binary: count it
                # against the spawn circuit breaker instead of blowing
                # up the scheduling pass (and every background caller
                # of _schedule) with FileNotFoundError.
                self._spawn_failures += 1
                log_f.write(
                    f"worker spawn failed: {e} (argv[0]={argv[0]})\n"
                    .encode())
                if tpu:
                    self._chip_alloc.release(worker_id)
                return None
        finally:
            log_f.close()
        w = WorkerHandle(worker_id, proc, tpu, image=image)
        self.workers[worker_id] = w
        return w

    def _log_tail_loop(self) -> None:
        """Forward new worker-log lines to this process's stderr with a
        `(worker pid=N)` prefix — the driver console on a head node."""
        import glob as _glob
        while not self._shutdown:
            time.sleep(0.25)
            try:
                for path in _glob.glob(os.path.join(self._log_dir,
                                                    "worker-*.log")):
                    off = self._log_offsets.get(path, 0)
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        continue
                    if size <= off:
                        continue
                    with open(path, "rb") as f:
                        f.seek(off)
                        chunk = f.read(size - off)
                    # Only forward complete lines; carry the remainder.
                    cut = chunk.rfind(b"\n")
                    if cut < 0:
                        continue
                    self._log_offsets[path] = off + cut + 1
                    tag = os.path.basename(path)[:-4]
                    for line in chunk[:cut].splitlines():
                        try:
                            sys.stderr.write(
                                f"({tag}) "
                                f"{line.decode(errors='replace')}\n")
                        except Exception:
                            pass
            except Exception:
                pass

    def _handle_worker_death(self, w: WorkerHandle, reason: str,
                             actor_already_handled: bool = False,
                             oom: bool = False) -> None:
        """Caller holds self.lock."""
        if w.state == "dead":
            return
        if w.state == "starting":
            self._spawn_failures += 1
            if self._spawn_failures >= self._spawn_failure_limit:
                err = exc.WorkerCrashedError(
                    f"{self._spawn_failures} consecutive workers died "
                    f"before registering (last: {reason}); worker "
                    "environment is broken — failing pending tasks")
                for rec in list(self.pending_queue):
                    self._fail_task_returns(rec, err)
                self.pending_queue.clear()
        if w.state == "busy":
            # ("blocked" workers already returned their resources when
            # they blocked — giving back again would double-credit.)
            self._release_held(w)
        w.state = "dead"
        self.workers.pop(w.worker_id, None)
        self._schedule_reap(w)
        rec = w.current_task
        if rec is not None and rec.state == "dispatched":
            if rec.retries_left > 0 and not rec.is_actor_creation \
                    and not rec.cancelled:
                self._schedule_retry(rec, "worker_crash", reason)
            else:
                err_cls = (exc.TaskCancelledError if rec.cancelled
                           else exc.OutOfMemoryError if oom
                           else exc.WorkerCrashedError)
                self._fail_task_returns(
                    rec, err_cls(
                        f"worker died while running "
                        f"{rec.spec.get('name')}: {reason}"))
                if rec.is_actor_creation and rec.actor_id is not None:
                    # A crash during __init__ must not strand the actor
                    # in 'pending' (method calls would hang forever) —
                    # restart or declare it dead.
                    actor = self.actors.get(rec.actor_id)
                    if actor is not None and actor.state != "dead":
                        self._on_actor_worker_death(
                            actor, f"worker died during creation: {reason}")
        if w.actor_id is not None and not actor_already_handled:
            actor = self.actors.get(w.actor_id)
            if actor is not None and actor.state != "dead":
                self._on_actor_worker_death(actor, reason)

    def _on_actor_worker_death(self, actor: ActorRecord, reason: str) -> None:
        """Caller holds self.lock."""
        # Fail or retry in-flight calls; restart if budget remains.  An
        # exit announced via exit_actor() keeps its intentional reason.
        if actor.intentional_exit:
            reason = actor.death_reason
        will_restart = (actor.restarts_left != 0
                        and not actor.intentional_exit)
        retried: List[TaskRecord] = []
        for rec in list(actor.in_flight.values()):
            if rec.cancelled:
                # Unreachable today (_h_cancel_task rejects actor
                # tasks) but load-bearing if cancellation ever extends
                # to them: a cancelled call must surface as cancelled,
                # never as a retryable/transient failure.
                self._fail_task_returns(rec, exc.TaskCancelledError(
                    f"task {rec.spec.get('name')!r} was cancelled"))
            elif will_restart and not rec.started:
                # Never began executing (sat in the dead worker's
                # queue): requeue for FREE — nothing ran, so nothing
                # can double, and no retry budget is owed.
                rec.state = "pending"
                rec.worker = None
                retried.append(rec)
            elif will_restart and rec.retries_left > 0:
                # max_task_retries: a STARTED call rides the restart —
                # back onto the head of the actor queue, re-dispatched
                # once the replacement worker is alive.
                rec.retries_left -= 1
                rec.state = "pending"
                rec.worker = None
                rec.started = False
                retried.append(rec)
                # delay 0: the resubmission is gated on the restart
                # itself, not a timer.
                self._emit_retry(rec, "actor_restart",
                                 f"actor restarting: {reason}", 0.0)
            elif will_restart:
                # The actor comes back but this started call's budget
                # is spent: typed TRANSIENT error (task_started=True —
                # a re-route could double its side effects; callers
                # decide).
                self._fail_task_returns(rec, exc.ActorUnavailableError(
                    actor.actor_id.hex(),
                    f"restarting after: {reason}",
                    task_started=True))
            else:
                self._fail_task_returns(rec, exc.ActorDiedError(
                    actor.actor_id.hex(), reason,
                    task_started=rec.started))
        actor.in_flight.clear()
        # Retried calls precede everything already queued, in their
        # original dispatch order.
        for rec in reversed(retried):
            actor.queue.appendleft(rec)
        actor.worker = None
        if actor.restarts_left != 0:
            if actor.restarts_left > 0:
                actor.restarts_left -= 1
            actor.state = "restarting"
            from ray_tpu.util.metrics import ACTOR_RESTARTS_METRIC
            self._inc_counter(ACTOR_RESTARTS_METRIC, {},
                              "actor restarts after worker death")
            creation = dict(actor.spec["creation_task"])
            creation["task_id"] = os.urandom(16)
            # Fresh return object for the restart's creation result.
            creation["return_ids"] = [os.urandom(16)]
            rec = TaskRecord(creation)
            # Init args produced before the first creation are READY now;
            # without pruning, stale deps would block the restart forever.
            rec.deps = {d for d in rec.deps if not self._object_ready(d)}
            if rec.had_deps and not rec.deps:
                rec.stages.setdefault("deps_fetched", time.time())
            self.tasks[rec.task_id] = rec
            for oid in creation["return_ids"]:
                e = self.objects.setdefault(oid, ObjectEntry())
                e.producing_task = rec.task_id
            self.pending_queue.append(rec)
            self._schedule()
        else:
            # Worker is already gone on this path (actor.worker was
            # cleared above); no teardown to do.
            self._mark_actor_dead(actor, reason,
                                  teardown_worker=False)

    def _fail_task_returns(self, rec: TaskRecord, error: Exception) -> None:
        """Caller holds self.lock."""
        blob = ser.dumps(error)
        rec.state = "done"
        self._emit_lifecycle(rec, prof=None, failed=True)
        self.tasks.pop(rec.task_id, None)
        try:
            self.pending_queue.remove(rec)
        except ValueError:
            pass
        for oid in rec.spec["return_ids"]:
            self._register_object(oid, "error", blob, len(blob),
                                  state=FAILED)
            if oid in self._streams:
                self.finish_stream(oid)   # wake parked consumers
        foreign_task = rec.spec.get("owner_node") not in (None,
                                                          self.node_id)
        if not rec.is_actor_creation and not foreign_task:
            for dep in rec.spec.get("embedded") or []:
                self._decref(dep)

    # ------------------------------------------------------------------
    # OOM defense (reference: src/ray/common/memory_monitor.h:52 +
    # raylet worker-killing policies, worker_killing_policy.h:34 /
    # worker_killing_policy_retriable_fifo.h:31)
    # ------------------------------------------------------------------
    @staticmethod
    def _host_memory_used_fraction() -> float:
        try:
            total = avail = None
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = float(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = float(line.split()[1])
                    if total is not None and avail is not None:
                        break
            if not total or avail is None:
                # No MemAvailable (exotic kernel): better a disabled
                # monitor than a kill-storm from reading "100% used".
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    @staticmethod
    def _rss_mb(pid: int) -> float:
        try:
            with open(f"/proc/{pid}/statm") as f:
                pages = int(f.read().split()[1])
            return pages * os.sysconf("SC_PAGE_SIZE") / 1e6
        except (OSError, ValueError, IndexError):
            return 0.0

    def _check_memory_pressure(self) -> None:
        """Kill one worker per check while the host is above the memory
        threshold.  Victim policy (reference retriable-FIFO +
        group-by-owner, simplified): retriable non-actor tasks first
        (their retry makes the kill recoverable), then non-retriable
        tasks, actors last; within a class, the newest-started first
        (least progress lost).  The killed task fails with a typed
        OutOfMemoryError that counts against its retries."""
        threshold = config.memory_usage_threshold
        if threshold >= 1.0:
            return
        used = self._host_memory_used_fraction()
        if used < threshold:
            return
        min_rss = config.memory_monitor_min_rss_mb
        with self.lock:
            candidates = []
            for w in self.workers.values():
                if w.state not in ("busy", "blocked"):
                    continue
                rss = self._rss_mb(w.pid)
                if rss < min_rss:
                    continue
                rec = w.current_task
                retriable = (rec is not None and rec.retries_left > 0
                             and not rec.is_actor_creation)
                is_actor = w.actor_id is not None
                klass = 0 if retriable and not is_actor else \
                    (1 if not is_actor else 2)
                candidates.append((klass, -w.last_idle_time, rss, w))
            if not candidates:
                return
            candidates.sort(key=lambda t: (t[0], t[1]))
            _, _, rss, victim = candidates[0]
            reason = (f"killed by the memory monitor: host memory at "
                      f"{used:.0%} >= threshold {threshold:.0%} "
                      f"(worker RSS {rss:.0f} MB)")
            try:
                if victim.proc is not None:
                    victim.proc.kill()
            except Exception:
                pass
            self._handle_worker_death(victim, reason, oom=True)
            self._schedule()

    def _recheck_infeasible(self) -> None:
        """Tasks admitted as pending demand while an autoscaler lease
        was fresh are re-checked when the lease expires: if the shape
        is unsatisfiable by any alive node's totals and nobody will
        ever provision it, fail it with the reason instead of leaving
        it pending forever (advisor round-2 finding)."""
        if self._autoscaler_live():
            return
        with self.lock:
            stale = []
            for rec in list(self.pending_queue):
                spec = rec.spec
                if spec.get("pg") is not None:
                    continue
                reason = self._infeasible_reason(spec.get("resources"))
                if reason is not None:
                    stale.append((rec, reason))
            for rec, reason in stale:
                if rec.is_actor_creation:
                    actor = self.actors.get(rec.actor_id)
                    if actor is not None:
                        # Queue failure matters here too: method calls
                        # queued while the actor was pending demand
                        # would otherwise hang their callers forever.
                        self._mark_actor_dead(
                            actor, f"infeasible: {reason}",
                            teardown_worker=False)
                self._fail_task_returns(rec, exc.InfeasibleResourceError(
                    f"task {rec.spec.get('name')!r} is infeasible and "
                    f"no autoscaler is alive to provision it: {reason}"))

    # ------------------------------------------------------------------
    # monitor: deadlines, dead procs, idle reaping
    # ------------------------------------------------------------------
    def _add_deadline_waiter(self, deadline: float,
                             cb: Callable[[], None]) -> None:
        """Register a timeout callback for the monitor to fire.  Wakes
        the monitor when the deadline lands inside the current tick so
        sub-50ms get/wait timeouts are honored precisely.

        Takes self.lock itself (reentrant — most callers already hold
        it): the monitor REBINDS _deadline_waiters under the lock each
        sweep, so an unlocked append can land on the superseded list
        and silently never fire (an RT010 self-finding)."""
        with self.lock:
            self._deadline_waiters.append((deadline, cb))
        if deadline - time.time() < 0.05:
            self._monitor_wake.set()

    # ------------------------------------------------------------------
    # stall sentinel (reference role: the dashboard reporter's py-spy
    # integration made automatic — stragglers get a targeted stack
    # capture recorded as a `stall` lifecycle event)
    # ------------------------------------------------------------------
    @staticmethod
    def _hist_quantile(cell: dict, q: float) -> float:
        """Upper-bound estimate of quantile `q` from an aggregated
        histogram cell — delegates to the shared implementation in
        util/metrics.py (one definition of "p95" for the stall
        sentinel, the slow-RPC sentinel, and the state APIs)."""
        from ray_tpu.util.metrics import hist_quantile
        return hist_quantile(cell, q)

    def _stall_threshold_locked(self) -> float:
        """max(stall_min_seconds, stall_p95_multiple * executing-stage
        p95) — the floor alone until enough tasks completed to make
        the histogram meaningful.  Caller holds self.lock."""
        from ray_tpu.util.metrics import TASK_STAGE_METRIC
        floor = config.stall_min_seconds
        key = (TASK_STAGE_METRIC, "histogram",
               (("stage", "executing"),))
        cell = self._metrics.get(key)
        if cell is None or (cell.get("count") or 0) \
                < config.stall_min_samples:
            return floor
        p95 = self._hist_quantile(cell, 0.95)
        return max(floor, config.stall_p95_multiple * p95)

    def _executing_tasks_locked(self):
        """(TaskRecord, WorkerHandle) pairs for everything currently
        executing user code on this node.  Caller holds self.lock."""
        for w in self.workers.values():
            rec = w.current_task
            if (rec is not None and w.state in ("busy", "blocked")
                    and rec.state == "dispatched"):
                yield rec, w
        for a in self.actors.values():
            if a.worker is None or a.worker.state == "dead":
                continue
            for rec in a.in_flight.values():
                # Dispatched-but-unstarted actor calls sit in the
                # worker's queue — queued, not stalled.
                if rec.started and rec.worker is None:
                    yield rec, a.worker

    def _stall_sentinel_tick(self) -> None:
        if not config.stall_detection_enabled \
                or config.stall_min_seconds <= 0:
            return
        now = time.time()
        flagged = []
        with self.lock:
            threshold = self._stall_threshold_locked()
            for rec, w in self._executing_tasks_locked():
                if rec.stall_reported:
                    continue
                start = (rec.stages.get("executing")
                         or rec.stages.get("worker_assigned"))
                if start is None or now - start < threshold:
                    continue
                rec.stall_reported = True
                flagged.append((rec, w, now - start, threshold))
        for rec, w, elapsed, threshold in flagged:
            self._capture_stall(rec, w, elapsed, threshold)

    def _capture_stall(self, rec: TaskRecord, w: WorkerHandle,
                       elapsed: float, threshold: float) -> None:
        """Targeted stack capture of the straggler's worker, recorded
        into the event ring as a `stall` lifecycle event (surfaced in
        summarize_tasks() and the chrome timeline)."""
        from ray_tpu.util.metrics import TASK_STALLS_METRIC
        name = rec.spec.get("name") or "<task>"

        def finish(stacks: dict, folded: dict) -> None:
            now = time.time()
            text = "\n".join(str(v) for v in stacks.values())
            with self.lock:
                self._inc_counter(
                    TASK_STALLS_METRIC, {},
                    "executing tasks flagged by the stall sentinel")
            self._emit_event({
                "kind": "stall",
                "name": name + ":stall",
                "task_name": name,
                "task_id": rec.task_id.hex(),
                "actor": rec.actor_id is not None,
                "elapsed_s": round(elapsed, 3),
                "threshold_s": round(threshold, 3),
                "stack": text,
                "pid": w.pid,
                "start": now, "end": now,
                "node_id": self.node_id.hex(),
            })

        self._request_worker_stacks([w], timeout=5.0, cb=finish)

    def _monitor_loop(self) -> None:
        # Event wait, not a fixed sleep (an RT005-class self-finding of
        # devtools/lint): shutdown() and a newly-registered near
        # deadline wake the loop immediately, so get/wait timeouts fire
        # on time instead of quantized to the next 50ms tick, and
        # shutdown never pays a last stale sleep.
        next_spill = next_infeasible = next_mem = next_scan = 0.0
        next_drain = next_stall = 0.0
        next_slow_rpc = next_hist = 0.0
        while not self._shutdown:
            with self.lock:
                nearest = min(
                    (d for d, _ in self._deadline_waiters),
                    default=None)
            timeout = 0.05
            if nearest is not None:
                timeout = max(0.0, min(timeout, nearest - time.time()))
            self._monitor_wake.wait(timeout)
            self._monitor_wake.clear()
            if self._shutdown:
                break
            now = time.time()
            # Periodic jobs are wall-clock scheduled (event wakes can
            # arrive much faster than the 50ms tick ever did).
            if now >= next_spill:     # ~1s: spill-threshold watchdog
                next_spill = now + 1.0
                try:
                    self._maybe_proactive_spill()
                except Exception:
                    pass
            if now >= next_infeasible:   # ~2s: infeasible recheck
                next_infeasible = now + 2.0
                try:
                    self._recheck_infeasible()
                except Exception:
                    pass
            if now >= next_drain:    # ~0.25s: preemption notice /
                next_drain = now + 0.25   # chaos preempt / drain sweep
                try:
                    self._drain_monitor_tick()
                except Exception:
                    pass
            if now >= next_stall:    # stall sentinel sweep
                next_stall = now + max(config.stall_check_interval_s,
                                       0.1)
                try:
                    self._stall_sentinel_tick()
                except Exception:
                    pass
            if now >= next_slow_rpc:   # slow-RPC sentinel sweep
                next_slow_rpc = now + max(
                    config.slow_rpc_check_interval_s, 0.1)
                try:
                    self._slow_rpc_tick()
                except Exception:
                    pass
            if now >= next_hist:     # metrics history ring sampler
                next_hist = now + max(
                    config.metrics_history_resolution_s, 0.05)
                try:
                    self._history_sample_tick()
                except Exception:
                    pass
            refresh_ms = config.memory_monitor_refresh_ms
            if refresh_ms > 0 and now >= next_mem:
                next_mem = now + refresh_ms / 1000.0
                try:
                    self._check_memory_pressure()
                except Exception:
                    pass
            # Deadline firing runs on EVERY wake (that is the point of
            # the event); the O(workers) death/idle/reap scans keep
            # their 50ms wall-clock cadence so a stream of sub-tick
            # timeouts can't turn them into wake-rate lock traffic.
            scan = now >= next_scan
            if scan:
                next_scan = now + 0.05
            fire = []
            with self.lock:
                remaining = []
                for deadline, cb in self._deadline_waiters:
                    if getattr(cb, "cancelled", False):
                        continue        # satisfied early: drop now
                    if now >= deadline:
                        fire.append(cb)
                    else:
                        remaining.append((deadline, cb))
                self._deadline_waiters = remaining
                if scan:
                    self._monitor_scan_locked(now)
            for cb in fire:
                try:
                    cb()
                except Exception:
                    pass

    def _monitor_scan_locked(self, now: float) -> None:
        """Worker-death / idle-reap / pending-reap sweep (caller holds
        self.lock; runs at the 50ms scan cadence, not per wake)."""
        for w in list(self.workers.values()):
            if (w.proc is not None and w.proc.poll() is not None
                    and w.state != "dead"):
                self._handle_worker_death(
                    w, f"worker process exited "
                       f"(code {w.proc.returncode})")
                self._schedule()
        idle_timeout = config.worker_idle_timeout_s
        for w in list(self.workers.values()):
            if (w.state == "idle" and w.actor_id is None
                    and now - w.last_idle_time > idle_timeout):
                w.state = "dead"
                self.workers.pop(w.worker_id, None)
                if w.conn_send:
                    w.conn_send({"type": "exit"})
                self._schedule_reap(w)
        still_pending = []
        for proc, pid, deadline in self._pending_reaps:
            if proc.poll() is not None:
                try:
                    self._store().reap_client(pid)
                except Exception:
                    pass
            elif now >= deadline:
                proc.kill()
                still_pending.append((proc, pid, now + 2.0))
            else:
                still_pending.append((proc, pid, deadline))
        self._pending_reaps = still_pending


def main() -> None:
    """Standalone node entry: one raylet-role process joining a cluster.

    python -m ray_tpu._private.node_service --gcs-host H --gcs-port P \
        [--resources '{"CPU": 4, "remote": 1}'] [--store-capacity BYTES]
    Prints NODE_READY=<node_id_hex> once serving (the Cluster fixture
    scrapes it).  Reference: raylet main (src/ray/raylet/main.cc)."""
    import argparse
    import json
    import signal

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs-host", required=True)
    ap.add_argument("--gcs-port", type=int, required=True)
    ap.add_argument("--resources", default="{}")
    ap.add_argument("--store-capacity", type=int, default=0)
    ap.add_argument("--session-prefix", default="")
    args = ap.parse_args()

    res = {k: float(v) for k, v in json.loads(args.resources).items()}
    res.setdefault("CPU", float(os.cpu_count() or 1))
    prefix = args.session_prefix or config.session_dir_prefix
    session_dir = os.path.join(
        prefix, f"node_{int(time.time()*1000)}_{os.getpid()}")
    os.makedirs(session_dir, exist_ok=True)
    store_path = f"/dev/shm/rtpu_node_{os.getpid()}"
    capacity = args.store_capacity or config.object_store_memory
    node = NodeService(session_dir, res, store_path, capacity,
                       gcs_address=(args.gcs_host, args.gcs_port))
    node.start()
    print(f"NODE_READY={node.node_id.hex()}", flush=True)

    stop = threading.Event()
    # Drain completion (clean or deadline-expired) ends the process.
    node._drain_exit_cb = stop.set

    def _on_sigterm(*_a) -> None:
        # First SIGTERM = preemption/maintenance notice: drain
        # gracefully (hand back work, migrate actors, re-replicate
        # sole object copies), then exit.  A second SIGTERM — or one
        # arriving mid-drain — forces an immediate stop.
        if node.draining:
            stop.set()
            return
        threading.Thread(
            target=node._begin_drain,
            args=("sigterm", "SIGTERM (drain requested)"),
            daemon=True, name="rtpu-sigterm-drain").start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    while not stop.is_set():
        stop.wait(0.5)
    node.shutdown()


if __name__ == "__main__":
    main()
