"""Serialization: cloudpickle + pickle-5 out-of-band zero-copy buffers.

Analog of the reference's `python/ray/_private/serialization.py` plus its
vendored cloudpickle: we use stock cloudpickle for closures/classes and
pickle protocol 5 `buffer_callback` to extract large contiguous buffers
(numpy arrays, bytes) out-of-band so they can be written into / read from
the shared-memory object store without copies.

Wire format of a stored object (all little-endian):

    u32 magic 'RTO1'
    u32 n_buffers
    u64 inband_len
    n_buffers * (u64 offset_from_start, u64 length)
    inband pickle bytes
    ...64-byte-aligned buffer payloads...

Deserialization maps buffers as memoryviews straight out of shared memory
(zero-copy for numpy via PickleBuffer).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

MAGIC = b"RTO1"
_ALIGN = 64
_HDR = struct.Struct("<4sIQ")
_BUF = struct.Struct("<QQ")


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class SerializedObject:
    """In-band bytes + out-of-band buffers, with total-size accounting."""

    __slots__ = ("inband", "buffers", "total_size")

    def __init__(self, inband: bytes, buffers: List[pickle.PickleBuffer]):
        self.inband = inband
        self.buffers = buffers
        size = _HDR.size + _BUF.size * len(buffers) + len(inband)
        for b in buffers:
            size = _align(size) + memoryview(b).nbytes
        self.total_size = size

    def write_into(self, dest: memoryview) -> int:
        """Serialize into a writable buffer; returns bytes written."""
        n = len(self.buffers)
        off = _HDR.size + _BUF.size * n + len(self.inband)
        offsets = []
        for b in self.buffers:
            off = _align(off)
            offsets.append((off, memoryview(b).nbytes))
            off += memoryview(b).nbytes
        _HDR.pack_into(dest, 0, MAGIC, n, len(self.inband))
        pos = _HDR.size
        for o, ln in offsets:
            _BUF.pack_into(dest, pos, o, ln)
            pos += _BUF.size
        dest[pos:pos + len(self.inband)] = self.inband
        for (o, ln), b in zip(offsets, self.buffers):
            mv = memoryview(b).cast("B")
            dest[o:o + ln] = mv
        return off

    def to_buffer(self) -> bytearray:
        """Serialize into a fresh bytearray WITHOUT the final
        bytearray->bytes copy.  For callers that only need a
        buffer-protocol payload (socket sends, pickle fields, file
        writes, memoryview deserialization) — bytearray satisfies all
        of them and pickles/loads transparently."""
        out = bytearray(self.total_size)
        self.write_into(memoryview(out))
        return out

    def to_bytes(self) -> bytes:
        return bytes(self.to_buffer())


def _device_arrays_to_host(obj: Any) -> Any:
    """jax.Arrays cannot cross processes; pull them to host numpy lazily.

    Registered as a cloudpickle reducer-by-value at serialize time via the
    persistent hooks below (we avoid importing jax unless it is already
    loaded, so the core runtime has no hard jax dependency).
    """
    return obj


class _RawBytes:
    """Sentinel marking a top-level large-bytes payload shipped
    out-of-band (collision-proof: compared by identity)."""


class _Pickler(cloudpickle.Pickler):
    def __init__(self, file, buffer_callback, ref_reducer=None):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self._ref_reducer = ref_reducer

    def reducer_override(self, obj):
        # jax.Array -> numpy (host transfer) — only if jax is loaded.
        import sys
        jax = sys.modules.get("jax")
        if jax is not None and isinstance(obj, jax.Array):
            import numpy as np
            return (np.asarray, (np.asarray(obj),))
        if self._ref_reducer is not None:
            r = self._ref_reducer(obj)
            if r is not None:
                return r
        # Delegate to cloudpickle's reducer_override — it implements
        # by-value pickling of lambdas/local functions there; returning
        # NotImplemented here would silently disable that.
        return super().reducer_override(obj)


def serialize(
    obj: Any,
    ref_reducer: Optional[Callable] = None,
) -> SerializedObject:
    """Serialize `obj`; `ref_reducer(obj)` may return a custom reduce tuple
    for ObjectRef instances (used by the worker layer to track borrows)."""
    buffers: List[pickle.PickleBuffer] = []

    def cb(buf: pickle.PickleBuffer) -> bool:
        # Only take large buffers out-of-band; small ones stay in-band.
        if memoryview(buf).nbytes >= 512:
            buffers.append(buf)
            return False
        return True

    # Top-level large bytes: pickle copies builtin bytes INTO the
    # inband stream (reducer_override is never consulted for them), so
    # a put(b"...") would pay 3x the memcpys of the numpy path.  Ship
    # the payload out-of-band under a sentinel instead (write side
    # zero-copy; one copy at read to rebuild the immutable bytes).
    if type(obj) is bytes and len(obj) >= 4096:
        inband = pickle.dumps((_RawBytes, pickle.PickleBuffer(obj)),
                              protocol=5, buffer_callback=cb)
        return SerializedObject(inband, buffers)
    f = io.BytesIO()
    _Pickler(f, cb, ref_reducer).dump(obj)
    # getbuffer(), not getvalue(): the view aliases the BytesIO's
    # internal buffer (kept alive by the view) instead of copying it —
    # inband bytes are only ever read through the buffer protocol.
    return SerializedObject(f.getbuffer(), buffers)


def deserialize(data: memoryview, copy_buffers: bool = False) -> Any:
    """Deserialize from a (possibly shared-memory-backed) buffer.

    With copy_buffers=False, returned numpy arrays alias `data` — callers
    must keep the backing store segment alive (the object store pins it
    via the ref count until released).
    """
    data = memoryview(data).cast("B")
    magic, n, inband_len = _HDR.unpack_from(data, 0)
    if magic != MAGIC:
        raise ValueError("Corrupt object header")
    pos = _HDR.size
    bufs = []
    for _ in range(n):
        o, ln = _BUF.unpack_from(data, pos)
        pos += _BUF.size
        mv = data[o:o + ln]
        if copy_buffers:
            mv = memoryview(bytes(mv))
        bufs.append(mv)
    inband = data[pos:pos + inband_len]
    out = pickle.loads(inband, buffers=bufs)
    if (type(out) is tuple and len(out) == 2
            and out[0] is _RawBytes):
        buf = out[1]
        if isinstance(buf, pickle.PickleBuffer):
            buf = buf.raw()
        return bytes(buf)
    return out


def dumps(obj: Any) -> bytes:
    """One-shot helper (control-plane messages, function table entries)."""
    return serialize(obj).to_bytes()


def loads(data: bytes) -> Any:
    return deserialize(memoryview(data), copy_buffers=True)
