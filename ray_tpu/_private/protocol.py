"""Message transport: length-prefixed pickle frames over unix sockets.

This is the local-node control-plane transport (analog of the reference's
gRPC layer, src/ray/rpc/).  Every client (driver or worker) keeps ONE
connection to its node service; replies are matched to requests by id, and
unsolicited pushes (task execution requests) are routed to a handler —
mirroring how the reference multiplexes PushTask onto core-worker gRPC
streams.

Chaos hooks replicate the reference's RAY_testing_rpc_failure /
RAY_testing_asio_delay_us env-driven fault injection (src/ray/rpc/
rpc_chaos.h:23, ray_config_def.h:833-841).  The injector itself lives in
_private/chaos.py (seeded, re-resolvable schedule); this layer holds the
hook points plus the rpc retry that absorbs injected pre-send failures
— the analog of the reference's gRPC-level retry on transient errors.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional

_LEN = struct.Struct("<Q")

# Pre-send failures (chaos-injected errors/drops) are retried this many
# times with exponential backoff before surfacing to the caller.
_RPC_RETRY_ATTEMPTS = 3
_RPC_RETRY_BASE_S = 0.01


class ConnectionLost(Exception):
    pass


# Re-exported singleton: the seeded chaos schedule (kept under the old
# `protocol.chaos` name for existing imports).  Imported AFTER
# ConnectionLost is defined — chaos.py raises it via a lazy import.
from ray_tpu._private.chaos import chaos  # noqa: E402


def _chaos_gate(msg_type: str, one_way: bool) -> bool:
    """Run the chaos hook with pre-send retry.

    Request/reply rpcs treat an injected drop like the reference treats
    a lost request — a (simulated) timeout absorbed by the retry loop.
    One-way notifies return True ("drop this message"): lossy by
    design, recovery belongs to a higher layer.  Raises ConnectionLost
    when injected failures out-budget the retry."""
    for attempt in range(_RPC_RETRY_ATTEMPTS + 1):
        try:
            action = chaos.maybe_inject(msg_type)
        except ConnectionLost:
            if attempt >= _RPC_RETRY_ATTEMPTS:
                raise
            time.sleep(_RPC_RETRY_BASE_S * (2 ** attempt))
            continue
        if action == "drop":
            if one_way:
                return True
            if attempt >= _RPC_RETRY_ATTEMPTS:
                raise ConnectionLost(
                    f"chaos: dropped rpc {msg_type}")
            time.sleep(_RPC_RETRY_BASE_S * (2 ** attempt))
            continue
        return False
    return False


# Frames below this size still concatenate header+payload (one syscall
# beats one tiny copy); larger payloads are sent as header then payload
# so the full-frame copy never happens.
_SEND_CONCAT_MAX = 64 * 1024


def send_msg(sock: socket.socket, msg: Any, lock: Optional[threading.Lock] = None) -> None:
    data = pickle.dumps(msg, protocol=5)
    header = _LEN.pack(len(data))
    # The caller-passed lock IS this connection's dedicated send
    # lock: holding it across sendall is its entire purpose (frame
    # interleaving corrupts the wire), hence the RT011 suppressions.
    if len(data) <= _SEND_CONCAT_MAX:
        frame = header + data
        if lock:
            with lock:
                sock.sendall(frame)  # ray-tpu: noqa[RT011]
        else:
            sock.sendall(frame)
        return
    if lock:
        with lock:
            sock.sendall(header)  # ray-tpu: noqa[RT011]
            sock.sendall(data)  # ray-tpu: noqa[RT011]
    else:
        sock.sendall(header)
        sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        try:
            chunk = sock.recv(min(n, 4 << 20))
        except (ConnectionResetError, OSError) as e:
            raise ConnectionLost(str(e)) from e
        if not chunk:
            raise ConnectionLost("socket closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


# ---------------------------------------------------------------------------
# binary object-transfer plane (reference: object_manager.h chunked
# pushes over dedicated channels).  No pickle anywhere on this path:
# requests and reply headers are fixed-layout structs and chunk payloads
# stream straight between the holder's mmap and the fetcher's
# pre-allocated shm buffer (recv_into).
#
#   request  (fetcher -> holder):  magic 'RTX1', object_id[16],
#                                  u64 offset, u64 length
#   response (holder -> fetcher):  u64 offset, u64 length, payload[length]
#
# One connection serves requests strictly in order, so the fetcher keeps
# a window of outstanding requests and matches replies FIFO.  length ==
# TRANSFER_ERR signals "not servable here" (object gone / truncated) and
# carries no payload.
# ---------------------------------------------------------------------------
TRANSFER_MAGIC = b"RTX1"
TRANSFER_REQ = struct.Struct("<4s16sQQ")
# Request body after the 4-byte magic (the serve loop peeks the magic
# first to tell chunk requests from channel-stream openings).
TRANSFER_REQ_BODY = struct.Struct("<16sQQ")
TRANSFER_RESP = struct.Struct("<QQ")
TRANSFER_ERR = (1 << 64) - 1

# ---------------------------------------------------------------------------
# compiled-DAG channel streams over the same transfer listener.  A
# cross-node channel edge opens ONE persistent connection and promotes
# it with magic 'RTC1'; after the opening frame every item is one
# length-prefixed write answered by an 8-byte ack (the ack doubles as
# per-item flow control: the receiver withholds it while the bounded
# destination queue is full).  No pickle framing, no control-plane
# dispatch — a cross-node hop costs one socket write.
#
#   open (sender -> receiver): magic 'RTC1', u16 key_len, u64 cap,
#                              key[key_len]
#   item (sender -> receiver): u64 length, payload[length]
#   ack  (receiver -> sender): u64 status (0 = ok, 1 = closed)
# ---------------------------------------------------------------------------
CHAN_MAGIC = b"RTC1"
CHAN_OPEN = struct.Struct("<HQ")
CHAN_ITEM = struct.Struct("<Q")
CHAN_ACK = struct.Struct("<Q")
CHAN_ACK_OK = 0
CHAN_ACK_CLOSED = 1


def recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill `view` completely from the socket (zero-copy receive)."""
    got = 0
    n = len(view)
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except (ConnectionResetError, OSError) as e:
            raise ConnectionLost(str(e)) from e
        if not r:
            raise ConnectionLost("socket closed mid-transfer")
        got += r


class Connection:
    """A request/reply + push connection over a unix socket.

    Thread-safe: any thread may `call` (blocking RPC) or `notify`
    (one-way); a dedicated receiver thread routes replies by request id
    and hands pushes to `push_handler`.
    """

    def __init__(self, sock: socket.socket,
                 push_handler: Optional[Callable[[dict], None]] = None,
                 on_disconnect: Optional[Callable[[], None]] = None) -> None:
        self._sock = sock
        self._send_lock = threading.Lock()
        self._push_handler = push_handler
        self._on_disconnect = on_disconnect
        self._pending: Dict[int, "_Waiter"] = {}
        self._pending_lock = threading.Lock()
        self._req_counter = 0
        self._closed = False
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="rtpu-conn-recv")
        self._recv_thread.start()

    def _next_req_id(self) -> int:
        with self._pending_lock:
            self._req_counter += 1
            return self._req_counter

    def _recv_loop(self) -> None:
        try:
            while True:
                msg = recv_msg(self._sock)
                rid = msg.get("__reply_to__")
                if rid is not None:
                    with self._pending_lock:
                        waiter = self._pending.pop(rid, None)
                    if waiter is not None:
                        waiter.set(msg)
                elif self._push_handler is not None:
                    self._push_handler(msg)
        except (ConnectionLost, pickle.UnpicklingError, EOFError):
            pass
        finally:
            self._closed = True
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for w in pending:
                w.fail(ConnectionLost("connection to node service lost"))
            if self._on_disconnect:
                self._on_disconnect()

    def call(self, msg: dict, timeout: Optional[float] = None) -> dict:
        """Blocking request/reply."""
        _chaos_gate(msg.get("type", "?"), one_way=False)
        if self._closed:
            raise ConnectionLost("connection closed")
        rid = self._next_req_id()
        msg["__req_id__"] = rid
        waiter = _Waiter()
        with self._pending_lock:
            self._pending[rid] = waiter
        send_msg(self._sock, msg, self._send_lock)
        reply = waiter.wait(timeout)
        if reply is None:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise TimeoutError(f"rpc {msg.get('type')} timed out")
        if isinstance(reply, Exception):
            raise reply
        err = reply.get("__error__")
        if err is not None:
            raise err if isinstance(err, Exception) else RuntimeError(err)
        return reply

    def notify(self, msg: dict) -> None:
        """One-way message (no reply expected)."""
        if _chaos_gate(msg.get("type", "?"), one_way=True):
            return      # chaos: message dropped on the floor
        send_msg(self._sock, msg, self._send_lock)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        # Join the recv thread (it wakes with ConnectionLost as soon
        # as the socket dies) — UNLESS close() is running ON it (an
        # on_disconnect callback closing its own connection), where a
        # join would self-deadlock.  An unjoined recv thread is the
        # RT014 class: it holds the fd's last reference and can fire
        # callbacks after the owner thinks the connection is gone.
        t = self._recv_thread
        if t is not threading.current_thread() and t.is_alive():
            t.join(timeout=2.0)


class _Waiter:
    __slots__ = ("_event", "_value")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Any = None

    def set(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def fail(self, exc: Exception) -> None:
        self._value = exc
        self._event.set()

    def wait(self, timeout: Optional[float]) -> Any:
        if not self._event.wait(timeout):
            return None
        return self._value


def wake_and_join_acceptor(thread, family: int, addr,
                           join_timeout: float = 2.0) -> None:
    """Wake a thread blocked in accept() with a dummy connection and join
    it BEFORE closing the listener fd.  A thread left in accept()
    survives close(); when the fd number is reused by a later listener,
    an EINTR retry can make the stale thread steal and instantly drop the
    new listener's first connection."""
    try:
        # Context manager: a refused/raced connect must not leak the
        # dummy socket until GC (RT013 self-finding).
        with socket.socket(family, socket.SOCK_STREAM) as s:
            s.settimeout(1.0)
            s.connect(addr)
    except OSError:
        pass
    if thread is not None and thread.is_alive():
        thread.join(timeout=join_timeout)


def connect_uds(path: str, deadline_s: float = 10.0) -> socket.socket:
    start = time.time()
    while True:
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(path)
            return sock
        except (FileNotFoundError, ConnectionRefusedError):
            if time.time() - start > deadline_s:
                raise
            time.sleep(0.02)


def connect_tcp(host: str, port: int, deadline_s: float = 10.0) -> socket.socket:
    start = time.time()
    while True:
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.connect((host, port))
            return sock
        except (ConnectionRefusedError, OSError):
            if time.time() - start > deadline_s:
                raise
            time.sleep(0.05)
