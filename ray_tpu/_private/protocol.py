"""Message transport: length-prefixed pickle frames over unix sockets.

This is the local-node control-plane transport (analog of the reference's
gRPC layer, src/ray/rpc/).  Every client (driver or worker) keeps ONE
connection to its node service; replies are matched to requests by id, and
unsolicited pushes (task execution requests) are routed to a handler —
mirroring how the reference multiplexes PushTask onto core-worker gRPC
streams.

Chaos hooks replicate the reference's RAY_testing_rpc_failure /
RAY_testing_asio_delay_us env-driven fault injection (src/ray/rpc/
rpc_chaos.h:23, ray_config_def.h:833-841) so failure-handling tests can
exercise retry paths deterministically.
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu._private.config import config

_LEN = struct.Struct("<Q")


class ConnectionLost(Exception):
    pass


# ---------------------------------------------------------------------------
# Chaos injection (reference: rpc_chaos.h)
# ---------------------------------------------------------------------------
class _Chaos:
    def __init__(self) -> None:
        self._fail_budget: Dict[str, int] = {}
        self._delays: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._parsed = False

    def _parse(self) -> None:
        if self._parsed:
            return
        self._parsed = True
        spec = config.testing_rpc_failure
        if spec:
            for part in spec.split(","):
                method, _, n = part.partition(":")
                self._fail_budget[method.strip()] = int(n or 1)
        dspec = config.testing_asio_delay_us
        if dspec:
            for part in dspec.split(","):
                method, lo, hi = part.split(":")
                self._delays[method.strip()] = (int(lo), int(hi))

    def maybe_inject(self, method: str) -> None:
        self._parse()
        if not self._fail_budget and not self._delays:
            return
        with self._lock:
            if method in self._delays:
                lo, hi = self._delays[method]
                time.sleep(random.uniform(lo, hi) / 1e6)
            budget = self._fail_budget.get(method, 0)
            if budget > 0 and random.random() < 0.5:
                self._fail_budget[method] = budget - 1
                raise ConnectionLost(f"chaos: injected failure for {method}")


chaos = _Chaos()


def send_msg(sock: socket.socket, msg: Any, lock: Optional[threading.Lock] = None) -> None:
    data = pickle.dumps(msg, protocol=5)
    frame = _LEN.pack(len(data)) + data
    if lock:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        try:
            chunk = sock.recv(min(n, 4 << 20))
        except (ConnectionResetError, OSError) as e:
            raise ConnectionLost(str(e)) from e
        if not chunk:
            raise ConnectionLost("socket closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


class Connection:
    """A request/reply + push connection over a unix socket.

    Thread-safe: any thread may `call` (blocking RPC) or `notify`
    (one-way); a dedicated receiver thread routes replies by request id
    and hands pushes to `push_handler`.
    """

    def __init__(self, sock: socket.socket,
                 push_handler: Optional[Callable[[dict], None]] = None,
                 on_disconnect: Optional[Callable[[], None]] = None) -> None:
        self._sock = sock
        self._send_lock = threading.Lock()
        self._push_handler = push_handler
        self._on_disconnect = on_disconnect
        self._pending: Dict[int, "_Waiter"] = {}
        self._pending_lock = threading.Lock()
        self._req_counter = 0
        self._closed = False
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="rtpu-conn-recv")
        self._recv_thread.start()

    def _next_req_id(self) -> int:
        with self._pending_lock:
            self._req_counter += 1
            return self._req_counter

    def _recv_loop(self) -> None:
        try:
            while True:
                msg = recv_msg(self._sock)
                rid = msg.get("__reply_to__")
                if rid is not None:
                    with self._pending_lock:
                        waiter = self._pending.pop(rid, None)
                    if waiter is not None:
                        waiter.set(msg)
                elif self._push_handler is not None:
                    self._push_handler(msg)
        except (ConnectionLost, pickle.UnpicklingError, EOFError):
            pass
        finally:
            self._closed = True
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for w in pending:
                w.fail(ConnectionLost("connection to node service lost"))
            if self._on_disconnect:
                self._on_disconnect()

    def call(self, msg: dict, timeout: Optional[float] = None) -> dict:
        """Blocking request/reply."""
        chaos.maybe_inject(msg.get("type", "?"))
        if self._closed:
            raise ConnectionLost("connection closed")
        rid = self._next_req_id()
        msg["__req_id__"] = rid
        waiter = _Waiter()
        with self._pending_lock:
            self._pending[rid] = waiter
        send_msg(self._sock, msg, self._send_lock)
        reply = waiter.wait(timeout)
        if reply is None:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise TimeoutError(f"rpc {msg.get('type')} timed out")
        if isinstance(reply, Exception):
            raise reply
        err = reply.get("__error__")
        if err is not None:
            raise err if isinstance(err, Exception) else RuntimeError(err)
        return reply

    def notify(self, msg: dict) -> None:
        """One-way message (no reply expected)."""
        chaos.maybe_inject(msg.get("type", "?"))
        send_msg(self._sock, msg, self._send_lock)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class _Waiter:
    __slots__ = ("_event", "_value")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Any = None

    def set(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def fail(self, exc: Exception) -> None:
        self._value = exc
        self._event.set()

    def wait(self, timeout: Optional[float]) -> Any:
        if not self._event.wait(timeout):
            return None
        return self._value


def wake_and_join_acceptor(thread, family: int, addr,
                           join_timeout: float = 2.0) -> None:
    """Wake a thread blocked in accept() with a dummy connection and join
    it BEFORE closing the listener fd.  A thread left in accept()
    survives close(); when the fd number is reused by a later listener,
    an EINTR retry can make the stale thread steal and instantly drop the
    new listener's first connection."""
    try:
        s = socket.socket(family, socket.SOCK_STREAM)
        s.settimeout(1.0)
        s.connect(addr)
        s.close()
    except OSError:
        pass
    if thread is not None and thread.is_alive():
        thread.join(timeout=join_timeout)


def connect_uds(path: str, deadline_s: float = 10.0) -> socket.socket:
    start = time.time()
    while True:
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.connect(path)
            return sock
        except (FileNotFoundError, ConnectionRefusedError):
            if time.time() - start > deadline_s:
                raise
            time.sleep(0.02)


def connect_tcp(host: str, port: int, deadline_s: float = 10.0) -> socket.socket:
    start = time.time()
    while True:
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.connect((host, port))
            return sock
        except (ConnectionRefusedError, OSError):
            if time.time() - start > deadline_s:
                raise
            time.sleep(0.05)
