"""CoreClient: the in-process runtime every driver and worker embeds.

Analog of the reference's CoreWorker (src/ray/core_worker/core_worker.h:271)
+ its Cython binding: task submission, put/get/wait, actor calls, the
function table cache, and ref counting — over one connection to the node
service plus direct (zero-copy) access to the shared-memory store.

Ref-counting protocol (single-directory variant of the reference's
ownership model, reference_count.h:64):
  * creating a ref (put / task return) => entry born with count 1, the
    creator's ObjectRef owns it;
  * a ref serialized INTO a stored object/task spec => +1 "embedded hold",
    owned by the containing entry/task and released when that entry is
    deleted (or the task finishes);
  * a ref deserialized FROM the wire => +1 announced at construction,
    -1 on GC.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import serialization as ser
from ray_tpu._private import tracing
from ray_tpu._private.config import config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.protocol import (Connection, connect_tcp,
                                       connect_uds)
from ray_tpu._private.shm_store import ShmObjectStore
from ray_tpu.object_ref import ObjectRef

_global_client: Optional["CoreClient"] = None
_global_lock = threading.Lock()


def get_global_client() -> Optional["CoreClient"]:
    return _global_client


def set_global_client(client: Optional["CoreClient"]) -> None:
    global _global_client
    with _global_lock:
        _global_client = client


class CoreClient:
    def __init__(self, socket_path: str, kind: str = "driver",
                 client_id: Optional[bytes] = None,
                 push_handler: Optional[Callable[[dict], None]] = None,
                 on_disconnect: Optional[Callable[[], None]] = None,
                 ) -> None:
        self.kind = kind
        self.client_id = client_id or os.urandom(16)
        sock = connect_uds(socket_path)
        self.conn = Connection(sock, push_handler=push_handler,
                               on_disconnect=on_disconnect)
        reply = self.conn.call({"type": "register_client", "kind": kind,
                                "client_id": self.client_id,
                                "pid": os.getpid()})
        self.store = ShmObjectStore(reply["store_path"])
        self.session_dir = reply["session_dir"]
        self._fn_cache: Dict[bytes, Any] = {}
        self._registered_fns: set = set()
        self._lock = threading.Lock()

    def close(self) -> None:
        self.conn.close()
        self.store.close()

    # ------------------------------------------------------------------
    # ref counting
    # ------------------------------------------------------------------
    def add_ref_async(self, oid: bytes) -> None:
        try:
            self.conn.notify({"type": "add_ref", "object_id": oid})
        except Exception:
            pass

    def remove_ref_async(self, oid: bytes) -> None:
        try:
            self.conn.notify({"type": "remove_ref", "object_id": oid})
        except Exception:
            pass

    # ------------------------------------------------------------------
    # serialization with ref extraction
    # ------------------------------------------------------------------
    def serialize_with_refs(self, obj: Any) -> Tuple[ser.SerializedObject,
                                                     List[bytes]]:
        embedded: List[bytes] = []

        def reducer(o):
            if isinstance(o, ObjectRef):
                embedded.append(o.binary())
                return (ObjectRef._from_wire, (o.binary(),))
            return None

        s = ser.serialize(obj, ref_reducer=reducer)
        # Embedded holds: +1 per occurrence, owned by the container.
        for oid in embedded:
            self.add_ref_async(oid)
        return s, embedded

    def _create_in_store(self, oid: ObjectID, size: int):
        """store.create with spill-on-full: a full store asks the node
        to spill sealed objects to disk, then retries (reference:
        plasma create retries + local_object_manager spilling)."""
        for attempt in range(3):
            try:
                return self.store.create(oid, size)
            except exc.ObjectStoreFullError:
                if attempt == 2:
                    raise
                self.conn.call({"type": "free_store_space",
                                "bytes": size})

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed "
                            "(matches the reference's behavior)")
        s, embedded = self.serialize_with_refs(value)
        oid = ObjectID.from_random()
        # One-way: registration is ordered ahead of any later RPC on this
        # connection (server processes a connection's frames in order),
        # so a subsequent get()/submit referencing the ref always finds
        # the directory entry.  Saves a round-trip per put (the hot path
        # the reference optimizes with plasma's async create).
        self._publish_value(oid.binary(), s, embedded, ack=False)
        return ObjectRef(oid.binary(), owned=True)

    def _publish_value(self, oid: bytes, s, embedded: List[bytes],
                       ack: bool) -> None:
        """THE inline-vs-shm publication step, shared by put() and
        put_with_id() so the loc decision and message shape can never
        diverge.  `ack` chooses acked call vs one-way notify."""
        send = self.conn.call if ack else self.conn.notify
        if (self.store is None
                or s.total_size <= config.max_direct_call_object_size):
            send({"type": "put_object", "object_id": oid,
                  "loc": "inline", "data": s.to_buffer(),
                  "size": s.total_size, "embedded": embedded})
            return
        buf = self._create_in_store(ObjectID(oid), s.total_size)
        s.write_into(buf)
        self.store.seal(ObjectID(oid))
        # Creator pin intentionally NOT released: the directory owns
        # it (unevictable while the entry lives) and releases it on
        # delete — the analog of the reference pinning primary copies.
        send({"type": "put_object", "object_id": oid,
              "loc": "shm", "data": None,
              "size": s.total_size, "embedded": embedded})

    def put_with_id(self, oid: bytes, value: Any,
                    as_error: bool = False) -> None:
        """Publish `value` under a caller-chosen object id — the bridge
        primitive behind relay/response refs (Serve router failover):
        the consumer blocks on `oid` while producers decide later which
        attempt's outcome lands there.  With as_error=True the value is
        an exception delivered as the object's FAILED tombstone (raised
        at get, like a task error).

        Uses acked calls, NOT one-way notifies: a silently dropped
        registration (chaos drop, connection blip) would strand the
        relay's reader in a permanent hang — the one failure mode this
        object must not have."""
        if as_error:
            blob = ser.dumps(value)
            self.conn.call({"type": "put_object", "object_id": oid,
                            "loc": "error", "data": blob,
                            "size": len(blob), "embedded": []})
            return
        s, embedded = self.serialize_with_refs(value)
        self._publish_value(oid, s, embedded, ack=True)

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        if not refs:
            return []      # no RPC — hot on the worker arg-unpack path
        oids = [r.binary() for r in refs]
        reply = self._blocking_call(
            {"type": "get_objects", "object_ids": oids, "timeout": timeout})
        if reply.get("timed_out"):
            raise exc.GetTimeoutError(
                f"get() timed out after {timeout}s")
        out = []
        for oid in oids:
            loc, data, size = reply["results"][oid]
            out.append(self._materialize_recovering(oid, loc, data))
        return out

    def _materialize(self, oid: bytes, loc: str, data: Optional[bytes]) -> Any:
        if loc == "inline":
            value = ser.deserialize(memoryview(data), copy_buffers=True)
        elif loc == "shm":
            mv = self.store.get_autoreleased_view(ObjectID(oid))
            if mv is None:
                raise exc.ObjectLostError(oid.hex(), "missing from shm store")
            # Zero-copy deserialize; the read pin auto-releases when the
            # last aliasing array is GC'd (see get_autoreleased_view).
            value = ser.deserialize(mv, copy_buffers=False)
        elif loc == "spilled":
            # Spilled to disk: read the file directly (data = path).
            try:
                with open(data.decode(), "rb") as f:
                    blob = f.read()
            except OSError as e:
                raise exc.ObjectLostError(
                    oid.hex(), f"spill file unreadable: {e}") from e
            value = ser.deserialize(memoryview(blob), copy_buffers=True)
        elif loc == "error":
            err = ser.loads(data)
            raise err
        else:
            raise exc.ObjectLostError(oid.hex(), f"unexpected loc {loc}")
        return value

    def _materialize_recovering(self, oid: bytes, loc: str,
                                data: Optional[bytes]) -> Any:
        """_materialize + one lineage-recovery round trip: a READY
        directory entry whose payload vanished (evicted, spill file
        lost) asks the node to recompute it from lineage, then re-gets
        (reference: object_recovery_manager.h:41)."""
        try:
            return self._materialize(oid, loc, data)
        except exc.ObjectLostError:
            if not self.conn.call({"type": "reconstruct_object",
                                   "object_id": oid}).get("ok"):
                raise
            reply = self._blocking_call(
                {"type": "get_objects", "object_ids": [oid],
                 "timeout": None})
            loc2, data2, _ = reply["results"][oid]
            return self._materialize(oid, loc2, data2)

    def object_sizes(self, refs: Sequence[ObjectRef]
                     ) -> List[Optional[int]]:
        """Known byte sizes of objects (None while pending/unknown)."""
        reply = self.conn.call(
            {"type": "object_sizes",
             "object_ids": [r.binary() for r in refs]})
        return reply["sizes"]

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        oids = [r.binary() for r in refs]
        reply = self._blocking_call(
            {"type": "wait", "object_ids": oids,
             "num_returns": num_returns, "timeout": timeout})
        ready_set = set(reply["ready"])
        ready, not_ready = [], []
        for r in refs:
            (ready if r.binary() in ready_set and len(ready) < num_returns
             else not_ready).append(r)
        return ready, not_ready

    def _blocking_call(self, msg: dict) -> dict:
        """RPC that may block server-side; workers report blocked status so
        the scheduler can backfill their CPU (avoids nested-task deadlock,
        reference: worker lease release on blocking Get)."""
        if self.kind != "worker":
            return self.conn.call(msg)
        probe = dict(msg)
        probe["timeout"] = 0
        reply = self.conn.call(probe)
        if not reply.get("timed_out"):
            if msg.get("timeout") == 0 or not _reply_incomplete(msg, reply):
                return reply
        self.conn.notify({"type": "worker_blocked"})
        try:
            return self.conn.call(msg)
        finally:
            self.conn.notify({"type": "worker_unblocked"})

    # ------------------------------------------------------------------
    # function table
    # ------------------------------------------------------------------
    def register_function(self, blob: bytes) -> bytes:
        fid = hashlib.sha1(blob).digest()[:16]
        with self._lock:
            if fid in self._registered_fns:
                return fid
        self.conn.call({"type": "fn_register", "function_id": fid,
                        "blob": blob})
        with self._lock:
            self._registered_fns.add(fid)
        return fid

    def fetch_function(self, fid: bytes) -> Any:
        with self._lock:
            if fid in self._fn_cache:
                return self._fn_cache[fid]
        reply = self.conn.call({"type": "fn_fetch", "function_id": fid})
        if reply["blob"] is None:
            raise RuntimeError(f"function {fid.hex()} not in table")
        import cloudpickle
        fn = cloudpickle.loads(reply["blob"])
        with self._lock:
            self._fn_cache[fid] = fn
        return fn

    # ------------------------------------------------------------------
    # task submission
    # ------------------------------------------------------------------
    def submit_task(self, function_id: bytes, name: str,
                    args: tuple, kwargs: dict, num_returns: int,
                    resources: Dict[str, float], retries: int,
                    actor_id: Optional[bytes] = None,
                    method_name: Optional[str] = None,
                    is_actor_creation: bool = False,
                    actor_spec_extra: Optional[dict] = None,
                    pg: Optional[dict] = None,
                    runtime_env: Optional[dict] = None,
                    affinity: Optional[dict] = None,
                    retry_exceptions=None,
                    ) -> List[ObjectRef]:
        spec_args, embedded = self._pack_args(args, kwargs)
        return_ids = [os.urandom(16) for _ in range(num_returns)]
        embedded = self._pin_runtime_env_archives(runtime_env, embedded)
        spec = {
            "task_id": os.urandom(16),
            "name": name,
            "function_id": function_id,
            "args": spec_args,
            "embedded": embedded,
            "num_returns": num_returns,
            "return_ids": return_ids,
            "resources": resources,
            "retries": retries,
            "actor_id": actor_id,
            "method_name": method_name,
            "is_actor_creation": is_actor_creation,
            "owner": self.client_id,
            "pg": pg,
            "runtime_env": runtime_env,
            "affinity": affinity,
            "submit_ts": time.time(),
            "trace_ctx": tracing.for_submit(),
            # True, or a tuple of exception types: application errors
            # matching it count as retryable (matched worker-side, see
            # worker_main._app_retryable).
            "retry_exceptions": retry_exceptions,
        }
        if actor_spec_extra:
            spec.update(actor_spec_extra)
        # Wire diet on the hottest rpc: these keys are only ever read
        # back via spec.get(...) server-side, so absent == default.
        # (actor_id/pg/resources are accessed directly and must stay.)
        for k in ("method_name", "runtime_env", "affinity",
                  "is_actor_creation", "trace_ctx", "retry_exceptions"):
            if not spec.get(k):
                del spec[k]
        # One-way submit: return ids are generated client-side and any
        # failure (infeasible, worker crash) is delivered through the
        # return objects — no reply to wait for.  This is what makes
        # submission pipeline (reference: lease reuse + PushTask stream).
        self.conn.notify({"type": "submit_task", "spec": spec})
        return [ObjectRef(oid, owned=True) for oid in return_ids]

    def _pin_runtime_env_archives(self, runtime_env: Optional[dict],
                                  embedded: List[bytes]) -> List[bytes]:
        """Archive refs must survive until the task runs: count them
        like embedded arg refs (+1 here, released by the node when the
        task completes) so the store keeps them pinned."""
        if not runtime_env:
            return embedded
        archives = ([runtime_env["working_dir"]]
                    if runtime_env.get("working_dir") else [])
        archives += runtime_env.get("py_modules") or []
        embedded = list(embedded)
        for a in archives:
            self.add_ref_async(a["ref"])
            embedded.append(a["ref"])
        return embedded

    def _pack_args(self, args: tuple, kwargs: dict
                   ) -> Tuple[List[tuple], List[bytes]]:
        """Top-level ObjectRef args become dependencies (resolved to values
        before execution, like the reference); everything else ships as one
        serialized (args, kwargs) blob with nested refs left as refs."""
        packed: List[tuple] = []
        all_embedded: List[bytes] = []
        positional: List[Any] = []
        for a in args:
            if isinstance(a, ObjectRef):
                self.add_ref_async(a.binary())   # held until task completes
                all_embedded.append(a.binary())
                packed.append(("ref", a.binary()))
                positional.append(None)          # placeholder slot
            else:
                positional.append(a)
        ref_slots = [i for i, a in enumerate(args)
                     if isinstance(a, ObjectRef)]
        kw_refs = {k: v.binary() for k, v in kwargs.items()
                   if isinstance(v, ObjectRef)}
        for k, oid in kw_refs.items():
            self.add_ref_async(oid)
            all_embedded.append(oid)
            packed.append(("ref", oid))
        plain_kwargs = {k: v for k, v in kwargs.items() if k not in kw_refs}
        s, embedded = self.serialize_with_refs(
            (positional, ref_slots, list(kw_refs.items()), plain_kwargs))
        all_embedded.extend(embedded)
        if s.total_size <= config.inline_small_args_size:
            packed.insert(0, ("inline", s.to_buffer()))
        else:
            oid = ObjectID.from_random()
            self._store_arg_blob(oid, s)
            packed.insert(0, ("blob", oid.binary()))
            all_embedded.append(oid.binary())
        return packed, all_embedded

    def _store_arg_blob(self, oid: ObjectID, s) -> None:
        """Publish an oversized arg blob (overridden by the thin client,
        which has no shared-memory segment)."""
        buf = self._create_in_store(oid, s.total_size)
        s.write_into(buf)
        self.store.seal(oid)  # creator pin kept — owned by directory
        self.conn.notify({"type": "put_object",
                          "object_id": oid.binary(),
                          "loc": "shm", "data": None,
                          "size": s.total_size, "embedded": []})

    def unpack_args(self, packed: List[tuple]) -> Tuple[tuple, dict]:
        """Worker side of _pack_args."""
        head = packed[0]
        if head[0] == "inline":
            payload = ser.deserialize(memoryview(head[1]), copy_buffers=True)
        else:  # blob in shm
            try:
                payload = self._materialize(head[1], "shm", None)
            except exc.ObjectLostError:
                # Forwarded task on another node: the args blob lives on
                # the owner node's store — resolve through the directory,
                # which pulls it across (multi-node path).
                reply = self._blocking_call(
                    {"type": "get_objects", "object_ids": [head[1]],
                     "timeout": None})
                loc, data, _ = reply["results"][head[1]]
                payload = self._materialize(head[1], loc, data)
        positional, ref_slots, kw_ref_items, plain_kwargs = payload
        ref_args = [t[1] for t in packed[1:] if t[0] == "ref"]
        n_pos = len(ref_slots)
        pos_values = self.get([ObjectRef._from_wire(o)
                               for o in ref_args[:n_pos]])
        for slot, v in zip(ref_slots, pos_values):
            positional[slot] = v
        kwargs = dict(plain_kwargs)
        kw_vals = self.get([ObjectRef._from_wire(oid)
                            for _, oid in kw_ref_items])
        for (k, _), v in zip(kw_ref_items, kw_vals):
            kwargs[k] = v
        return tuple(positional), kwargs

    # ------------------------------------------------------------------
    # task results (worker side)
    # ------------------------------------------------------------------
    def build_return_meta(self, oid: bytes, value: Any) -> tuple:
        """Returns (oid, loc, data, size, embedded_refs) for task_done."""
        s, embedded = self.serialize_with_refs(value)
        if s.total_size <= config.max_direct_call_object_size:
            return (oid, "inline", s.to_buffer(), s.total_size, embedded)
        obj = ObjectID(oid)
        try:
            buf = self._create_in_store(obj, s.total_size)
        except exc.ObjectStoreFullError:
            # Even after spilling READY objects the store can stay full
            # of OTHER in-flight tasks' sealed-but-unregistered returns
            # (not yet spillable).  Write this return straight to a
            # spill file instead of deadlocking the pipeline.
            if not config.object_spilling_enabled:
                raise
            spill_dir = (config.object_spilling_dir
                         or os.path.join(self.session_dir, "spill"))
            os.makedirs(spill_dir, exist_ok=True)
            path = os.path.join(spill_dir, oid.hex())
            with open(path, "wb") as f:
                f.write(s.to_buffer())
            return (oid, "spilled", path.encode(), s.total_size,
                    embedded)
        except FileExistsError:
            # A prior attempt of this task died around create/seal
            # (ADVICE r1).  reset_stale frees the leftover (CREATING or
            # sealed-but-unregistered) iff its creator is dead; then we
            # write fresh — keeping `embedded` consistent with the
            # payload.  If the creator is somehow still alive (death
            # detection raced), fall back to reusing its sealed copy.
            if self.store.reset_stale(obj):
                buf = self._create_in_store(obj, s.total_size)
            else:
                mv = self.store.get(obj)
                if mv is None:
                    raise
                return (oid, "shm", None, len(mv), embedded)
        s.write_into(buf)
        self.store.seal(obj)  # creator pin kept — owned by directory
        return (oid, "shm", None, s.total_size, embedded)

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def create_actor(self, class_id: bytes, name_repr: str, args: tuple,
                     kwargs: dict, resources: Dict[str, float],
                     max_restarts: int, max_concurrency: int,
                     name: Optional[str], namespace: str,
                     detached: bool,
                     pg: Optional[dict] = None,
                     runtime_env: Optional[dict] = None,
                     affinity: Optional[dict] = None
                     ) -> Tuple[bytes, ObjectRef]:
        actor_id = os.urandom(16)
        spec_args, embedded = self._pack_args(args, kwargs)
        embedded = self._pin_runtime_env_archives(runtime_env, embedded)
        creation_task = {
            "task_id": os.urandom(16),
            "name": f"{name_repr}.__init__",
            "function_id": class_id,
            "args": spec_args,
            "embedded": embedded,
            "num_returns": 1,
            "return_ids": [os.urandom(16)],
            "resources": resources,
            "retries": 0,
            "actor_id": actor_id,
            "method_name": None,
            "is_actor_creation": True,
            "max_concurrency": max_concurrency,
            "owner": self.client_id,
            "pg": pg,
            "runtime_env": runtime_env,
            "submit_ts": time.time(),
            "trace_ctx": tracing.for_submit(),
        }
        spec = {
            "actor_id": actor_id,
            "name": name,
            "class_name": name_repr,
            "namespace": namespace,
            "detached": detached,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "class_id": class_id,
            "resources": resources,
            "creation_task": creation_task,
            "pg": pg,
            "affinity": affinity,
        }
        self.conn.call({"type": "create_actor", "spec": spec})
        return actor_id, ObjectRef(creation_task["return_ids"][0],
                                   owned=True)

    def submit_actor_task(self, actor_id: bytes, class_id: bytes,
                          method_name: str, args: tuple, kwargs: dict,
                          num_returns, retries: int = 0):
        if num_returns == "streaming":
            refs = self.submit_task(
                function_id=class_id, name=method_name, args=args,
                kwargs=kwargs, num_returns=1, resources={},
                retries=0, actor_id=actor_id, method_name=method_name,
                actor_spec_extra={"streaming": True})
            from ray_tpu.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(refs[0], self)
        return self.submit_task(
            function_id=class_id, name=method_name, args=args,
            kwargs=kwargs, num_returns=num_returns, resources={},
            retries=retries, actor_id=actor_id, method_name=method_name)

    def cancel_task(self, object_id: bytes, force: bool = False) -> dict:
        return self.conn.call({"type": "cancel_task",
                               "object_id": object_id, "force": force})

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        self.conn.call({"type": "kill_actor", "actor_id": actor_id,
                        "no_restart": no_restart})

    def actor_state(self, actor_id: bytes) -> dict:
        return self.conn.call({"type": "actor_state", "actor_id": actor_id})

    def lookup_named_actor(self, name: str, namespace: str) -> dict:
        return self.conn.call({"type": "lookup_named_actor", "name": name,
                               "namespace": namespace})

    def list_named_actors(self, namespace: Optional[str]) -> List[str]:
        return self.conn.call({"type": "list_named_actors",
                               "namespace": namespace})["names"]

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------
    def kv_put(self, ns: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        return self.conn.call({"type": "kv_put", "ns": ns, "key": key,
                               "value": value, "overwrite": overwrite})["ok"]

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        return self.conn.call({"type": "kv_get", "ns": ns,
                               "key": key})["value"]

    def kv_wait(self, ns: str, key: bytes,
                timeout: float) -> Optional[bytes]:
        """Blocking kv read: parked node-side until the key exists or
        `timeout` elapses (returns None)."""
        return self.conn.call({"type": "kv_wait", "ns": ns, "key": key,
                               "timeout": timeout},
                              timeout=timeout + 20.0)["value"]

    def kv_del(self, ns: str, key: bytes) -> bool:
        return self.conn.call({"type": "kv_del", "ns": ns, "key": key})["ok"]

    def kv_keys(self, ns: str, prefix: bytes = b"") -> List[bytes]:
        return self.conn.call({"type": "kv_keys", "ns": ns,
                               "prefix": prefix})["keys"]

    def node_info(self) -> dict:
        return self.conn.call({"type": "node_info"})

    def actor_node(self, actor_id: bytes) -> bytes:
        """Home node of an actor (compiled-DAG channel routing)."""
        return self.conn.call({"type": "actor_node",
                               "actor_id": actor_id})["node_id"]

    # -- compiled-DAG channel plane (cross-node channels) ---------------
    def chan_send(self, dst_node: bytes, key: bytes, value: Any,
                  cap: int = 8, timeout: Optional[float] = None) -> None:
        from ray_tpu.experimental.channel import ChannelClosed
        rep = self.conn.call({"type": "chan_send", "dst": dst_node,
                              "key": key, "payload": ser.dumps(value),
                              "cap": cap}, timeout=timeout)
        if rep.get("closed"):
            raise ChannelClosed(key.decode(errors="replace"))

    def chan_recv(self, key: bytes,
                  timeout: Optional[float] = None) -> Any:
        from ray_tpu.experimental.channel import ChannelClosed
        msg: dict = {"type": "chan_recv", "key": key}
        call_timeout = None
        if timeout is not None:
            # Expiry is node-side (the reply always comes from under
            # the queue lock) so an abandoned parked reply can never
            # swallow a delivered item; the rpc timeout is only a
            # safety margin on top.
            msg["block_ms"] = int(timeout * 1000)
            call_timeout = timeout + 10.0
        rep = self.conn.call(msg, timeout=call_timeout)
        if rep.get("closed"):
            raise ChannelClosed(key.decode(errors="replace"))
        if rep.get("timeout"):
            raise TimeoutError(f"chan_recv timed out")
        return ser.loads(rep["payload"])

    def chan_close(self, dst_node: Optional[bytes], key: bytes) -> None:
        self.conn.call({"type": "chan_close", "dst": dst_node,
                        "key": key}, timeout=15.0)

    # -- streaming generators ----------------------------------------------
    def stream_next(self, stream_id: bytes, index: int) -> dict:
        """Block until stream item `index` exists or the stream ends.
        The node parks the reply (no client-side polling)."""
        return self.conn.call({"type": "stream_next",
                               "stream_id": stream_id,
                               "index": index}, timeout=None)

    def stream_release(self, stream_id: bytes) -> None:
        try:
            self.conn.notify({"type": "stream_release",
                              "stream_id": stream_id})
        except Exception:
            pass

    def stream_yield(self, stream_id: bytes, item_meta: tuple) -> None:
        self.conn.notify({"type": "stream_yield",
                          "stream_id": stream_id, "item": item_meta})

    # -- observability -----------------------------------------------------
    def state_dump(self, cluster: bool = True) -> dict:
        return self.conn.call({"type": "state_dump",
                               "cluster": cluster}, timeout=30.0)["dump"]

    def metrics_push(self, series: List[dict]) -> None:
        self.conn.call({"type": "metrics_push", "series": series})

    def metrics_scrape(self) -> List[dict]:
        return self.conn.call({"type": "metrics_scrape"})["series"]

    def timeline_events(self, cluster: bool = True) -> List[dict]:
        return self.conn.call({"type": "timeline",
                               "cluster": cluster},
                              timeout=30.0)["events"]

    def profile_event(self, event: dict) -> None:
        self.conn.notify({"type": "profile_event", "event": event})

    # -- placement groups --------------------------------------------------
    def create_pg(self, pg_id: bytes, bundles: List[Dict[str, float]],
                  strategy: str, name: Optional[str],
                  ready_oid: bytes) -> None:
        self.conn.call({"type": "create_pg", "pg_id": pg_id,
                        "bundles": bundles, "strategy": strategy,
                        "name": name, "ready_oid": ready_oid})

    def remove_pg(self, pg_id: bytes) -> bool:
        return self.conn.call({"type": "remove_pg", "pg_id": pg_id})["ok"]

    def pg_state(self, pg_id: bytes) -> dict:
        return self.conn.call({"type": "pg_state", "pg_id": pg_id})

    def cluster_resources(self) -> dict:
        return self.conn.call({"type": "cluster_resources"})

    def store_stats(self) -> dict:
        return self.conn.call({"type": "store_stats"})["stats"]


def _reply_incomplete(msg: dict, reply: dict) -> bool:
    if msg["type"] == "wait":
        return len(reply.get("ready", [])) < msg["num_returns"]
    return False


class RemoteCoreClient(CoreClient):
    """Thin-client variant: same control protocol over TCP, NO local
    shared-memory segment (reference: ray.util.client's proxied
    CoreWorker surface).  Differences from the in-node client:

    * `put` always ships the serialized value in the put_object RPC
      (the node holds it in its directory); there is no zero-copy path
      from a remote machine.
    * "shm"/"spilled" results are pulled through the node's
      object-transfer endpoints (fetch_object_meta/chunk) — the same
      plane peers use — then deserialized with copies.
    """

    def __init__(self, host: str, port: int,
                 client_id: Optional[bytes] = None,
                 push_handler: Optional[Callable[[dict], None]] = None,
                 ) -> None:
        self.kind = "driver"
        self.client_id = client_id or os.urandom(16)
        sock = connect_tcp(host, port)
        self.conn = Connection(sock, push_handler=push_handler)
        reply = self.conn.call({"type": "register_client",
                                "kind": "driver",
                                "client_id": self.client_id,
                                "pid": os.getpid()})
        self.store = None
        self.session_dir = reply["session_dir"]
        self._fn_cache: Dict[bytes, Any] = {}
        self._registered_fns: set = set()
        self._lock = threading.Lock()

    def close(self) -> None:
        self.conn.close()

    # -- object plane over RPC ------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed "
                            "(matches the reference's behavior)")
        s, embedded = self.serialize_with_refs(value)
        oid = ObjectID.from_random()
        self.conn.notify({"type": "put_object",
                          "object_id": oid.binary(),
                          "loc": "inline", "data": s.to_buffer(),
                          "size": s.total_size, "embedded": embedded})
        return ObjectRef(oid.binary(), owned=True)

    def _store_arg_blob(self, oid: ObjectID, s) -> None:
        # No local segment: oversized args ship inline in the RPC and
        # live in the node's directory like thin-client put()s.
        self.conn.notify({"type": "put_object",
                          "object_id": oid.binary(),
                          "loc": "inline", "data": s.to_buffer(),
                          "size": s.total_size, "embedded": []})

    def _materialize(self, oid: bytes, loc: str,
                     data: Optional[bytes]) -> Any:
        if loc in ("shm", "spilled"):
            blob = self._fetch_remote(oid)
            return ser.deserialize(memoryview(blob), copy_buffers=True)
        return super()._materialize(oid, loc, data)

    def _fetch_remote(self, oid: bytes) -> bytes:
        meta = self.conn.call({"type": "fetch_object_meta",
                               "object_id": oid}, timeout=60.0)
        if not meta.get("found"):
            raise exc.ObjectLostError(oid.hex(),
                                      "not fetchable from node")
        if meta["kind"] == "error":
            raise ser.loads(meta["data"])
        if meta.get("data") is not None:
            return meta["data"]
        total = meta["size"]
        chunk = config.object_transfer_chunk_bytes
        parts = []
        off = 0
        while off < total:
            r = self.conn.call({"type": "fetch_object_chunk",
                                "object_id": oid, "offset": off,
                                "length": min(chunk, total - off)},
                               timeout=60.0)
            # Chunk replies carry "data" (no "found" key) — mirror the
            # node's own peer-pull loop, including the empty-chunk
            # abort (a truncated backing copy must not spin forever).
            if not r.get("data"):
                raise exc.ObjectLostError(oid.hex(),
                                          "evicted during fetch")
            parts.append(r["data"])
            off += len(r["data"])
        return b"".join(parts)
