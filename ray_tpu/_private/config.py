"""Central config registry.

TPU-native analog of the reference's single C++ config registry
(`src/ray/common/ray_config_def.h` — 217 RAY_CONFIG(type, name, default)
entries, each overridable via a `RAY_<name>` env var).  We keep the same
shape: every knob is declared once here, typed, defaulted, and overridable
via `RAY_TPU_<NAME>` environment variables or programmatically via
``ray_tpu.init(_system_config={...})``.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


@dataclass
class _ConfigEntry:
    name: str
    type: type
    default: Any
    doc: str = ""


class ConfigRegistry:
    """Typed, env-overridable config registry (singleton at module scope)."""

    def __init__(self) -> None:
        self._entries: Dict[str, _ConfigEntry] = {}
        self._overrides: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def declare(self, name: str, type_: type, default: Any, doc: str = "") -> None:
        self._entries[name] = _ConfigEntry(name, type_, default, doc)

    def get(self, name: str) -> Any:
        entry = self._entries[name]
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
        env = os.environ.get(_ENV_PREFIX + name.upper())
        if env is not None:
            if entry.type is bool:
                return _parse_bool(env)
            return entry.type(env)
        return entry.default

    def set(self, name: str, value: Any) -> None:
        entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"Unknown config: {name}")
        with self._lock:
            self._overrides[name] = entry.type(value)

    def update(self, overrides: Dict[str, Any]) -> None:
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def reset(self) -> None:
        with self._lock:
            self._overrides.clear()

    def __getattr__(self, name: str) -> Any:
        # Attribute-style access: config.object_store_memory
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None

    def describe(self) -> Dict[str, Any]:
        return {n: self.get(n) for n in self._entries}


config = ConfigRegistry()
_D = config.declare

# ---------------------------------------------------------------------------
# Core runtime
# ---------------------------------------------------------------------------
_D("object_store_memory", int, 256 * 1024 * 1024,
   "Bytes of shared memory for the per-node object store.")
_D("object_store_min_alloc", int, 64, "Allocation granularity / alignment.")
_D("max_direct_call_object_size", int, 100 * 1024,
   "Results <= this many bytes are returned inline (in-process memory "
   "store) instead of the shared-memory store.  Mirrors the reference's "
   "max_direct_call_object_size (ray_config_def.h).")
_D("worker_register_timeout_s", float, 30.0,
   "Seconds to wait for a spawned worker process to register.")
_D("task_default_num_cpus", float, 1.0, "Default CPU requirement per task.")
_D("actor_default_num_cpus", float, 0.0,
   "Default CPU requirement for an actor process (reference default: "
   "actors reserve 0 CPUs when running, 1 for placement).")
_D("worker_pool_prestart", int, 0, "Workers to prestart on init.")
_D("worker_idle_timeout_s", float, 600.0,
   "Idle worker processes are reaped after this many seconds.")
_D("heartbeat_interval_s", float, 1.0, "Node -> GCS heartbeat period.")
_D("health_check_failure_threshold", int, 5,
   "Missed heartbeats before a node is marked dead (reference: "
   "health_check_failure_threshold).")
_D("scheduler_spread_threshold", float, 0.5,
   "Utilization below which the hybrid policy packs; above, spreads "
   "(reference: scheduler_spread_threshold).")
_D("scheduler_top_k_fraction", float, 0.2,
   "Top-k fraction for hybrid scheduling randomization.")
_D("max_pending_lease_requests_per_scheduling_category", int, 10,
   "Pipelined lease requests per scheduling key (reference name kept).")
_D("max_task_retries", int, 3, "Default retries for normal tasks.")
_D("max_actor_restarts", int, 0, "Default actor restarts.")
_D("log_to_driver", bool, True, "Forward worker stdout/stderr to driver.")
_D("session_dir_prefix", str, "/tmp/ray_tpu",
   "Prefix for per-session scratch directories.")
_D("inline_small_args_size", int, 100 * 1024,
   "Task args <= this many bytes are shipped inline in the task spec.")
_D("testing_rpc_failure", str, "",
   "Chaos (legacy): 'method:max_failures' pairs, comma separated — "
   "injected failures in the message layer (reference: "
   "RAY_testing_rpc_failure).  Folded into the chaos_spec schedule.")
_D("testing_asio_delay_us", str, "",
   "Chaos (legacy): 'method:min:max' artificial delays in message "
   "dispatch (reference: RAY_testing_asio_delay_us).  Folded into the "
   "chaos_spec schedule.")
_D("chaos_seed", int, 0,
   "Seed for the chaos fault-injection RNG (_private/chaos.py): the "
   "same seed + workload replays the identical injected-fault trace.")
_D("chaos_spec", str, "",
   "Chaos schedule: comma-separated 'site:key=value:...' entries "
   "(kinds: error, drop, delay, kill_worker, evict, kill_replica, "
   "partition, preempt).  See _private/chaos.py for the grammar; "
   "validate with `ray_tpu chaos`.")
_D("drain_grace_s", float, 30.0,
   "Default grace for a graceful node drain: running tasks get this "
   "long to finish (and actors/objects to migrate) before the node "
   "falls back to the kill-and-retry path and exits.")
_D("preemption_notice_file", str, "",
   "Path polled (~4x/s) by the node monitor: when the file appears, "
   "the node treats it as a TPU preemption notice and begins a "
   "graceful drain.  File contents: empty (use drain_grace_s), a "
   "float (seconds until the deadline), or JSON {\"deadline_s\": N}. "
   "A GCE metadata-watcher shim or a test writes this file.")
_D("gcs_wal_fsync", bool, True,
   "fsync the GCS write-ahead log.  Critical records (named-actor /"
   " node-membership transitions, snapshots) fsync on append; hot-path"
   " records (KV, small-object payloads) batch into one fsync per"
   " gcs_wal_fsync_batch_s window.  Off trades an OS-crash durability"
   " window for append latency (a GCS process crash alone never loses"
   " flushed records).")
_D("gcs_wal_fsync_batch_s", float, 0.05,
   "Max seconds of flushed-but-unsynced hot-path WAL records an OS "
   "crash may lose when gcs_wal_fsync is on.")
_D("gcs_wal_compact_ops", int, 2000,
   "WAL records appended since the last snapshot that trigger "
   "snapshot + log compaction (gcs.snap written, gcs.wal truncated).")
_D("gcs_wal_compact_bytes", int, 8 * 1024 * 1024,
   "WAL size in bytes that triggers snapshot + log compaction "
   "regardless of record count.")
_D("gcs_call_timeout_s", float, 10.0,
   "Default per-call deadline for node->GCS rpcs: a dead-but-connected "
   "GCS surfaces as a timeout into the reconnect/retry path instead of "
   "wedging the caller forever.")
_D("gcs_reconnect_max_s", float, 60.0,
   "Total time a GCS call rides out an outage (transparent reconnect "
   "with exponential backoff) before surfacing ConnectionLost; nodes "
   "keep working on cached locations/actor homes meanwhile.")
_D("gcs_reconnect_delay_ms", int, 50,
   "Base backoff between GCS reconnect attempts; doubles per attempt "
   "with jitter up to gcs_reconnect_max_delay_ms.")
_D("gcs_reconnect_max_delay_ms", int, 2000,
   "Upper bound on the per-attempt GCS reconnect backoff.")
_D("gcs_resync_grace_s", float, 10.0,
   "After a GCS restart, recovered (stale) node records get this long "
   "to reconnect and re-sync before the health check reaps them.")
_D("gcs_status_interval_s", float, 10.0,
   "How often the node monitor polls gcs_status (feeds the "
   "ray_tpu_gcs_wal_bytes gauge and epoch-change detection).")
_D("task_retry_delay_ms", int, 50,
   "Base backoff before a task retry is resubmitted; doubles per "
   "attempt with jitter (reference role: task resubmit backoff).")
_D("task_retry_max_delay_ms", int, 5000,
   "Upper bound on the per-retry backoff delay.")
_D("object_store_prefault", bool, True,
   "Write-touch every store page at creation so puts never pay "
   "first-touch page faults (~4x single-copy put bandwidth).")
_D("object_spilling_enabled", bool, True,
   "Spill sealed objects to disk when the store fills (reference: "
   "automatic_object_spilling_enabled).")
_D("object_spilling_threshold", float, 0.8,
   "Fraction of the object store that may fill before spilling begins.")
_D("object_spilling_dir", str, "",
   "Directory for spilled objects (default: <session_dir>/spill).")
_D("min_spilling_size", int, 1024 * 1024,
   "Batch spills until at least this many bytes are queued.")
_D("max_object_reconstructions", int, 3,
   "Times a lost object may be recomputed from lineage before its "
   "readers get ObjectLostError (reference: max_task_retries role in "
   "object_recovery_manager).")
_D("object_transfer_chunk_bytes", int, 4 * 1024 * 1024,
   "Chunk size for inter-node object transfer (reference: "
   "object_manager_default_chunk_size, 5 MiB).")
_D("object_transfer_window", int, 8,
   "Outstanding chunk requests pipelined per transfer stream "
   "(reference: object_manager_max_bytes_in_flight role).  <=1 falls "
   "back to stop-and-wait chunk RPCs over the control connection.")
_D("object_transfer_parallelism", int, 4,
   "Max concurrent source nodes for a range-split parallel fetch of "
   "one large object.")
_D("object_transfer_multisource_min_bytes", int, 16 * 1024 * 1024,
   "Objects at least this large with multiple holders are fetched as "
   "contiguous ranges from several holders in parallel.")
_D("object_pull_workers", int, 8,
   "Bounded worker pool for the object pull manager (replaces "
   "thread-per-object pulls; reference: pull_manager.h request "
   "pipelining).")
_D("locality_spill_threshold_bytes", int, 1024 * 1024,
   "A queued task whose locally-resident dependency bytes reach this "
   "threshold (and dominate every candidate peer's resident bytes) "
   "briefly waits for local capacity instead of spilling to a "
   "dependency-less node (reference: locality-aware spillback in "
   "cluster_task_manager).")
_D("locality_spill_wait_s", float, 1.0,
   "How long a locality-dominant task waits for local capacity before "
   "spilling anyway.")
_D("dag_spin_us", int, 50,
   "Compiled-graph channel wait: microseconds of pure spin before the "
   "wait degrades to sched_yield (~20ms) and then escalating sleeps.  "
   "Spin covers the hot pipelined case (peer answers within µs); "
   "raise it on dedicated cores, lower it (or 0) when executors "
   "outnumber cores — a spinning waiter steals cycles the producing "
   "stage needs.")
_D("kv_block_size", int, 16,
   "Paged-KV serving: tokens per KV block.  Every request's cache is a "
   "list of fixed-size blocks from a shared pool (vLLM/RPA-style paged "
   "attention); only FULL blocks are prefix-shareable, so smaller "
   "blocks share more but cost more gather indices per decode step.")
_D("kv_num_blocks", int, 0,
   "Paged-KV serving: usable blocks in the shared pool.  0 = auto "
   "(num_slots * ceil(max_len / kv_block_size) — same HBM footprint "
   "as the dense per-slot cache, with sharing as pure upside).")
_D("prefix_cache_enabled", bool, True,
   "Paged-KV serving: keep retired requests' full prompt blocks in a "
   "per-model radix tree so later prompts sharing the prefix decode "
   "from cached blocks (prefill runs only the uncached suffix).")
_D("kv_eviction_policy", str, "lru",
   "Paged-KV serving: how cached (refcount-0) prefix blocks are "
   "reclaimed when the free pool empties.  Only 'lru' is implemented; "
   "the knob exists so a different policy is a config change, not an "
   "API change.")
_D("serve_compiled_pipeline", bool, False,
   "Serve fast lane: route unary deployment requests through a "
   "per-replica compiled graph (router handoff writes into the "
   "graph's input channel) instead of a scheduled actor task per "
   "call.  Streaming requests always use the task path.")

# ---------------------------------------------------------------------------
# TPU / mesh execution layer
# ---------------------------------------------------------------------------
_D("tpu_chips_per_host", int, 4, "Chips per TPU host (v5e/v5p default 4).")
_D("mesh_default_axes", str, "dp,fsdp,tp",
   "Default logical mesh axis names, outer to inner.")
_D("train_report_queue_size", int, 64, "Buffered train.report() messages.")
_D("prefetch_buffer_size", int, 2,
   "Device prefetch depth for host->HBM input pipelines.")
_D("memory_usage_threshold", float, 0.95,
   "Host-memory used fraction above which the memory monitor kills a "
   "worker (reference: memory_monitor.h); >= 1.0 disables killing.")
_D("memory_monitor_refresh_ms", int, 1000,
   "Memory-monitor poll period; 0 disables the monitor.")
_D("memory_monitor_min_rss_mb", float, 64.0,
   "Workers below this RSS are never chosen as OOM-kill victims.")
_D("profile_events_max", int, 10_000,
   "Per-node ring capacity for profile/trace events (ray.timeline "
   "analog; reference: RAY_PROFILING event table).")
_D("event_ring_capacity", int, 0,
   "Per-node lifecycle/profile event ring capacity; 0 falls back to "
   "profile_events_max.  Evictions from the full ring are counted in "
   "ray_tpu_events_dropped_total so long-running clusters can see "
   "lifecycle history silently rolling off.")
_D("stall_detection_enabled", bool, True,
   "Stall sentinel: the node monitor compares every executing task's "
   "elapsed time against the executing-stage latency histogram and "
   "auto-captures the worker's stack when it exceeds the threshold "
   "(a 'stall' lifecycle event; reference role: the dashboard "
   "reporter's py-spy integration, made automatic).")
_D("stall_min_seconds", float, 60.0,
   "Stall sentinel floor: a task is never flagged before running this "
   "long, regardless of the p95-derived threshold.  The effective "
   "threshold is max(stall_min_seconds, stall_p95_multiple * p95).")
_D("stall_p95_multiple", float, 3.0,
   "Stall threshold as a multiple of the executing-stage p95 from the "
   "node's ray_tpu_task_stage_duration_seconds histogram.")
_D("stall_min_samples", int, 10,
   "Minimum completed-task samples in the executing-stage histogram "
   "before its p95 participates in the stall threshold (below this, "
   "only the stall_min_seconds floor applies).")
_D("stall_check_interval_s", float, 2.0,
   "How often the node monitor sweeps executing tasks for stalls.")
_D("train_telemetry_enabled", bool, True,
   "Training telemetry plane (train/telemetry.py): per-step phase "
   "decomposition, live MFU/goodput accounting, and cross-host "
   "straggler detection for train sessions.")
_D("train_telemetry_window", int, 128,
   "Rolling window of per-step records kept (and published) by each "
   "train worker's telemetry session — feeds step-time percentiles "
   "and the straggler reducer.")
_D("train_telemetry_publish_s", float, 1.0,
   "How often a train worker's telemetry session publishes its "
   "snapshot (phase totals, goodput ledger, step window) to the "
   "control-plane KV for state.train_summary() / `ray_tpu train "
   "status`.  A publisher thread keeps snapshots fresh even while a "
   "step is wedged.")
_D("train_span_min_interval_s", float, 0.25,
   "Rate limit for train-step timeline spans: per-step driver events "
   "are BATCHED into one span per interval (the PR-8 lesson — an "
   "unbatched per-step notify re-introduces ms-scale stalls on fast "
   "step loops).  0 emits one span per step.")
_D("train_straggler_multiple", float, 1.5,
   "A worker is flagged as the gang's straggler when its step-phase "
   "p95 exceeds the gang median p95 by this multiple (>= 2 workers, "
   "train_straggler_min_steps samples each).")
_D("train_straggler_min_steps", int, 5,
   "Minimum step samples in a worker's telemetry window before it "
   "participates in straggler detection.")
_D("train_straggler_check_s", float, 2.0,
   "How often the trainer driver runs the straggler reducer over the "
   "workers' published step windows (each newly flagged rank gets ONE "
   "targeted stack capture via the stall-sentinel dump path).")
_D("train_input_bound_fraction", float, 0.3,
   "A run is classified input-bound when data_wait takes at least "
   "this fraction of attributed step time (the ingest-vs-compute "
   "verdict in state.train_summary() / `ray_tpu train status`).")
_D("train_mfu_halflife_s", float, 30.0,
   "Half-life of the exponentially decayed window behind the live "
   "tokens/s and MFU readouts (recent steps dominate; a paused run "
   "decays toward zero instead of averaging it away).")
_D("train_elastic_enabled", bool, False,
   "Elastic gang training (train/elastic.py): workers publish sharded "
   "in-cluster checkpoints, and a preempted worker triggers a gang "
   "RESIZE (survivors reshard from the object-store checkpoint and "
   "continue at N-1) instead of a restart-from-disk at fixed world "
   "size; the gang grows back when capacity heals.")
_D("train_ckpt_interval_s", float, 30.0,
   "Cadence of the elastic in-cluster sharded checkpoint: each worker "
   "snapshots its shard of params/opt_state into the object store at "
   "most this often (0 = every step — tests).  The keeper commits a "
   "manifest once every member's shard for a step has arrived.")
_D("train_ckpt_keep", int, 2,
   "Committed in-cluster checkpoint steps the keeper pins at once; "
   "older steps' shard refs are released only AFTER a newer manifest "
   "is registered (never drop the last live copy).")
_D("train_min_world_size", int, 1,
   "Elastic shrink floor: a resize below this many workers is refused "
   "and the failure falls through to the fixed-world restart path.")
_D("train_elastic_poll_s", float, 0.25,
   "How often an elastic worker checks the gang record for an epoch "
   "change (resize) or a preemption notice, and the driver polls for "
   "grow-back capacity.")
_D("train_grow_retry_s", float, 2.0,
   "Elastic grow-back probe cadence: after a shrink, the driver "
   "attempts to re-expand the gang to its full world size at most "
   "this often (each attempt spawns a replacement worker which "
   "reshards from the in-cluster checkpoint).")
_D("train_resize_thrash_per_min", float, 4.0,
   "Doctor threshold for GANG_RESIZE_THRASH: a run whose resize rate "
   "over its lifetime exceeds this many resizes/min is flagged — the "
   "gang is spending its time resharding, not training.")
_D("workflow_storage_dir", str, "",
   "Durable workflow storage root (default: ~/.ray_tpu/workflows). "
   "Deliberately outside the session dir so resume survives shutdown.")
_D("lint_mode", str, "warn",
   "Decoration-time static analysis on @remote/@actor (devtools/lint): "
   "'warn' emits RayTpuLintWarning, 'error' raises LintError, 'off' "
   "disables the check.")
# The lock sanitizer itself has NO config knob on purpose: it is
# enabled ONLY by the RAY_TPU_LOCKSAN env var, read at `import
# ray_tpu` (devtools/locksan.py) — _system_config is applied far too
# late to instrument import-time locks and would not inherit into
# spawned node/worker processes, so a config switch would be a
# silent no-op trap.
_D("lock_hold_warn_ms", float, 500.0,
   "Locksan: a lock held longer than this is recorded as a long-hold "
   "finding (site, duration, holder stack) in the locksan report — "
   "the live counterpart of lint rule RT011's "
   "blocking-call-under-lock class.")
_D("locksan_dir", str, "",
   "Locksan: directory where each process drops its <pid>.json "
   "report for `ray_tpu locksan` / state.locksan_report() to merge "
   "(default /tmp/ray_tpu_locksan; RAY_TPU_LOCKSAN_DIR overrides).")
# The leak ledger follows locksan's rules exactly: enabled ONLY by the
# RAY_TPU_LEAKSAN env var (read at `import ray_tpu`, inherited by
# spawned processes); only the report directory is a config knob.
_D("leaksan_dir", str, "",
   "Leaksan: directory where each process drops its <pid>.json "
   "resource ledger for `ray_tpu leaksan` / state.leaksan_report() "
   "to merge (default /tmp/ray_tpu_leaksan; RAY_TPU_LEAKSAN_DIR "
   "overrides).")
# The XLA sanitizer follows the same rules: enabled ONLY by the
# RAY_TPU_XLASAN env var (read at `import ray_tpu`, inherited by
# spawned processes — jax.jit must be patched before user code grabs
# a reference); only the report directory is a config knob.
_D("xlasan_dir", str, "",
   "Xlasan: directory where each process drops its <pid>.json "
   "recompile/host-sync ledger for `ray_tpu xlasan` / "
   "state.xlasan_report() to merge (default /tmp/ray_tpu_xlasan; "
   "RAY_TPU_XLASAN_DIR overrides).")
_D("metrics_history_resolution_s", float, 2.0,
   "Metrics history ring: sampling interval of the node monitor's "
   "per-series (ts, value) recorder behind state.metric_history() / "
   "/api/metrics/history / `ray_tpu top`.  Counters sample their "
   "running total, gauges their last value, histograms their "
   "observation count.")
_D("metrics_history_window_s", float, 600.0,
   "Metrics history ring: how much trailing history each series "
   "keeps (ring capacity = window / resolution samples; older "
   "samples are evicted).")
_D("metrics_history_max_series", int, 512,
   "Metrics history ring: cap on distinct (name, tags) series "
   "tracked per node — past it, new series are not recorded (bounds "
   "memory under tag-cardinality explosions).")
_D("slow_rpc_min_seconds", float, 1.0,
   "Slow-RPC sentinel floor: an in-flight control-plane handler is "
   "never flagged before running this long (the stall sentinel's "
   "stall_min_seconds, at RPC scale).")
_D("slow_rpc_p95_multiple", float, 5.0,
   "Slow-RPC sentinel: with enough samples, a handler is flagged "
   "when it exceeds this multiple of its method's server-side p95 — "
   "the effective threshold is max(floor, multiple * p95).")
_D("slow_rpc_min_samples", int, 20,
   "Minimum completed-RPC samples in a method's server histogram "
   "before its p95 participates in the slow-RPC threshold (below "
   "this, only the slow_rpc_min_seconds floor applies).")
_D("slow_rpc_capture_window_s", float, 30.0,
   "Slow-RPC sentinel rate limit: at most ONE stack + args capture "
   "per method per this window (the flag counter still increments "
   "for every flagged handler).")
_D("slow_rpc_check_interval_s", float, 2.0,
   "How often the node monitor sweeps in-flight RPC handlers for "
   "slow-RPC flags.")
_D("sched_span_min_interval_s", float, 1.0,
   "Rate limit for sampled `sched.decide` timeline spans: scheduler "
   "decisions are BATCHED into at most one span per interval per "
   "node (the PR-8 hot-path lesson — the per-decision counters and "
   "the recent-decision ring are always on; only span emission is "
   "sampled).  0 emits one span per scheduling pass.")
