"""The single source of truth for `@remote`/`.options()` keys.

The reference scatters its option tables across ray_option_utils.py
(task_options / actor_options dicts); here both live in one module so
the decorators, the `.options()` merge path, and the static analyzer
(devtools/lint rule RT003) validate against the SAME tables — a typo'd
key produces the same suggestion everywhere.
"""

from __future__ import annotations

import difflib
from typing import Any, Dict, FrozenSet, Iterable, Optional

# Options shared by tasks and actors.
COMMON_OPTIONS: FrozenSet[str] = frozenset({
    "num_cpus", "num_tpus", "resources", "name",
    "placement_group", "placement_group_bundle_index",
    "runtime_env", "scheduling_strategy", "_affinity",
})

# Task-only options.
TASK_OPTIONS: FrozenSet[str] = COMMON_OPTIONS | {
    "num_returns", "max_retries", "retry_exceptions",
}

# Actor-only options.
ACTOR_OPTIONS: FrozenSet[str] = COMMON_OPTIONS | {
    "max_restarts", "max_concurrency", "namespace", "lifetime",
    "max_task_retries",
}


def suggest(key: str, valid: Iterable[str]) -> Optional[str]:
    """Closest valid key for a typo, or None if nothing is close."""
    matches = difflib.get_close_matches(key, list(valid), n=1, cutoff=0.6)
    return matches[0] if matches else None


def validate_options(options: Dict[str, Any], valid: FrozenSet[str],
                     kind: str) -> None:
    """Raise ValueError for unknown keys, naming the closest valid key.

    `kind` is "task" or "actor" (used in the message so an actor option
    passed to a task reads as a kind mismatch, not a typo).
    """
    bad = sorted(set(options) - valid)
    if not bad:
        return
    hints = []
    for key in bad:
        # Cross-kind check FIRST: `max_restarts` on a task is a kind
        # mismatch, not a typo — a fuzzy "did you mean max_retries?"
        # would send the user the wrong way.
        if key in (ACTOR_OPTIONS | TASK_OPTIONS):
            other = "actor" if kind == "task" else "task"
            hints.append(f"{key!r} (valid only for {other}s, "
                         f"not {kind}s)")
            continue
        near = suggest(key, valid)
        if near is not None and near != key:
            hints.append(f"{key!r} (did you mean {near!r}?)")
        else:
            hints.append(repr(key))
    raise ValueError(
        f"invalid {kind} options: {', '.join(hints)}; valid keys: "
        f"{sorted(valid)}")
