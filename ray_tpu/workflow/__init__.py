"""Workflows: durable task-DAG execution with checkpoint/resume.

Reference surface: python/ray/workflow/api.py (run :92, run_async,
resume :276, get_output, get_status, list_all, delete) executing task
DAGs built with `.bind` (the modern DAG-based workflow API), with every
step's result persisted to workflow storage so a crashed/interrupted
workflow resumes from its last completed step
(workflow/workflow_storage.py).

Storage is a filesystem directory (config `workflow_storage_dir`),
deliberately OUTSIDE the session directory: durability must survive
`ray_tpu.shutdown()` and process death.  Each step's result is written
atomically to `<storage>/<workflow_id>/steps/<step_key>.pkl`; status
transitions land in `meta.json`.

Dynamic workflows: a step that returns a DAG node (continuation) has
that sub-DAG executed in its place, checkpointed under a nested key —
the reference's `workflow.continuation` pattern.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.remote_function import RemoteFunction

__all__ = ["run", "run_async", "resume", "get_status", "get_output",
           "list_all", "delete", "FunctionNode", "EventNode",
           "wait_for_event"]

RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"


# ---------------------------------------------------------------------------
# task DAG nodes (`fn.bind(...)`)
# ---------------------------------------------------------------------------
class FunctionNode:
    def __init__(self, rf: RemoteFunction, args: tuple,
                 kwargs: dict) -> None:
        self.rf = rf
        self.args = args
        self.kwargs = kwargs

    def __repr__(self) -> str:
        return f"{self.rf.__name__}.bind(...)"


# ---------------------------------------------------------------------------
# storage
# ---------------------------------------------------------------------------
def _storage_root() -> str:
    from ray_tpu._private.config import config
    root = config.workflow_storage_dir or os.path.expanduser(
        "~/.ray_tpu/workflows")
    os.makedirs(root, exist_ok=True)
    return root


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root(), workflow_id)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _write_meta(workflow_id: str, **updates) -> dict:
    path = os.path.join(_wf_dir(workflow_id), "meta.json")
    meta = {}
    if os.path.exists(path):
        with open(path) as f:
            meta = json.load(f)
    meta.setdefault("workflow_id", workflow_id)
    meta.update(updates, update_time=time.time())
    _atomic_write(path, json.dumps(meta).encode())
    return meta


def _read_meta(workflow_id: str) -> Optional[dict]:
    path = os.path.join(_wf_dir(workflow_id), "meta.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------
def _step_key(node: FunctionNode, prefix: str, index: int) -> str:
    """Stable identity: position in the (deterministic) DAG walk + the
    function name.  Argument VALUES are deliberately not hashed — a
    resumed run must match keys even when unpicklable refs differ."""
    name = getattr(node.rf, "__name__", "step")
    raw = f"{prefix}/{index}/{name}"
    return (f"{name}-"
            f"{hashlib.sha256(raw.encode()).hexdigest()[:12]}")


class _Execution:
    def __init__(self, workflow_id: str) -> None:
        self.workflow_id = workflow_id
        self.steps_dir = os.path.join(_wf_dir(workflow_id), "steps")
        os.makedirs(self.steps_dir, exist_ok=True)
        # Per-run memo: a node consumed by several downstream nodes is
        # one STEP, executed once (DAG, not tree, semantics).  Values
        # are (node, result) — holding the node keeps its id() from
        # being recycled onto a fresh node by the allocator.
        self._memo: Dict[int, tuple] = {}

    def _load(self, key: str):
        path = os.path.join(self.steps_dir, f"{key}.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return pickle.load(f)

    def _store(self, key: str, value: Any) -> None:
        _atomic_write(os.path.join(self.steps_dir, f"{key}.pkl"),
                      pickle.dumps({"value": value}, protocol=5))

    def exec_node(self, node: Any, prefix: str = "",
                  counter: Optional[List[int]] = None) -> Any:
        """Post-order DAG execution with per-step checkpointing."""
        if counter is None:
            counter = [0]
        if isinstance(node, EventNode):
            return self._exec_event(node, prefix, counter)
        if not isinstance(node, FunctionNode):
            return node                     # constant argument
        if id(node) in self._memo:
            return self._memo[id(node)][1]
        my_index = counter[0]
        counter[0] += 1
        # Children first (deterministic order → deterministic keys).
        args = [self.exec_node(a, prefix, counter) for a in node.args]
        kwargs = {k: self.exec_node(v, prefix, counter)
                  for k, v in sorted(node.kwargs.items())}
        key = _step_key(node, prefix, my_index)
        cached = self._load(key)
        if cached is not None:
            value = cached["value"]
        else:
            value = ray_tpu.get(node.rf.remote(*args, **kwargs))
            if isinstance(value, (FunctionNode, EventNode)):
                # Continuation: the step produced a sub-DAG (or an
                # event wait); its result IS this step's result
                # (nested key space).
                value = self.exec_node(value, prefix=f"{prefix}/{key}",
                                       counter=[0])
            self._store(key, value)
        self._memo[id(node)] = (node, value)
        return value

    def _exec_event(self, node: "EventNode", prefix: str,
                    counter: List[int]) -> Any:
        """Durable external event: poll the listener until it yields a
        non-None payload, checkpoint it — a resumed workflow that
        already observed the event NEVER waits again (reference:
        workflow/api.py wait_for_event + event listeners)."""
        if id(node) in self._memo:
            return self._memo[id(node)][1]
        my_index = counter[0]
        counter[0] += 1
        name = getattr(node.listener, "__name__", "event")
        raw = f"{prefix}/{my_index}/event/{name}"
        key = (f"event-{name}-"
               f"{hashlib.sha256(raw.encode()).hexdigest()[:12]}")
        cached = self._load(key)
        if cached is not None:
            value = cached["value"]
        else:
            while True:
                value = node.listener(*node.args, **node.kwargs)
                if value is not None:
                    break
                time.sleep(node.poll_interval_s)
            self._store(key, value)
        self._memo[id(node)] = (node, value)
        return value


class EventNode:
    """DAG node for an external event: `listener(*args)` is polled
    until it returns non-None; the payload becomes the node's value
    and is checkpointed durably."""

    def __init__(self, listener, args: tuple, kwargs: dict,
                 poll_interval_s: float) -> None:
        self.listener = listener
        self.args = args
        self.kwargs = kwargs
        self.poll_interval_s = poll_interval_s

    def __repr__(self) -> str:
        name = getattr(self.listener, "__name__", "event")
        return f"EventNode({name})"


def wait_for_event(listener, *args, poll_interval_s: float = 0.1,
                   **kwargs) -> EventNode:
    """Bind an external-event step into a workflow DAG (reference:
    workflow.wait_for_event).  `listener` is a plain callable returning
    None while the event is pending and the (picklable) payload once
    fired; the payload is durable — resume never re-waits."""
    return EventNode(listener, args, kwargs, poll_interval_s)


def run(dag: FunctionNode, workflow_id: Optional[str] = None) -> Any:
    """Execute a task DAG durably; blocks for the result
    (api.py:92)."""
    workflow_id = workflow_id or f"wf-{os.urandom(6).hex()}"
    if not isinstance(dag, FunctionNode):
        raise TypeError("workflow.run expects a DAG built with "
                        "remote_fn.bind(...)")
    os.makedirs(_wf_dir(workflow_id), exist_ok=True)
    _write_meta(workflow_id, status=RUNNING, start_time=time.time())
    ex = _Execution(workflow_id)
    # The DAG structure must survive for resume: store it (cloudpickle —
    # @remote wrappers shadow their module names, so plain pickle's
    # by-reference lookup fails; best effort for truly unpicklable
    # closures).
    try:
        import cloudpickle
        _atomic_write(os.path.join(_wf_dir(workflow_id), "dag.pkl"),
                      cloudpickle.dumps(dag))
    except Exception:
        pass
    try:
        result = ex.exec_node(dag)
    except BaseException as e:
        _write_meta(workflow_id, status=FAILED, error=repr(e))
        raise
    # Output FIRST, then the SUCCEEDED flip: a crash between the two
    # must leave a resumable RUNNING record, never a "successful"
    # workflow with no recoverable output.
    _atomic_write(os.path.join(_wf_dir(workflow_id), "output.pkl"),
                  pickle.dumps({"value": result}, protocol=5))
    _write_meta(workflow_id, status=SUCCEEDED)
    return result


def run_async(dag: FunctionNode,
              workflow_id: Optional[str] = None) -> "threading.Thread":
    """Fire-and-track: runs on a daemon thread; poll with
    get_status/get_output."""
    workflow_id = workflow_id or f"wf-{os.urandom(6).hex()}"
    t = threading.Thread(target=lambda: _swallow(run, dag, workflow_id),
                         daemon=True, name=f"rtpu-wf-{workflow_id}")
    t.workflow_id = workflow_id   # type: ignore[attr-defined]
    t.start()
    return t


def _swallow(fn, *a):
    try:
        fn(*a)
    except BaseException:
        pass                      # status already recorded as FAILED


def resume(workflow_id: str) -> Any:
    """Re-run from storage: completed steps short-circuit from their
    checkpoints (api.py:276)."""
    meta = _read_meta(workflow_id)
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if meta["status"] == SUCCEEDED:
        return get_output(workflow_id)
    dag_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(
            f"workflow {workflow_id!r} has no stored DAG (its driver "
            f"crashed before the first checkpoint); re-run it")
    with open(dag_path, "rb") as f:
        dag = pickle.load(f)
    return run(dag, workflow_id=workflow_id)


def get_status(workflow_id: str) -> str:
    meta = _read_meta(workflow_id)
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return meta["status"]


def get_output(workflow_id: str) -> Any:
    path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(path):
        status = get_status(workflow_id)
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={status})")
    with open(path, "rb") as f:
        return pickle.load(f)["value"]


def list_all(status_filter: Optional[str] = None) -> List[dict]:
    out = []
    root = _storage_root()
    for wid in sorted(os.listdir(root)):
        meta = _read_meta(wid)
        if meta and (status_filter is None
                     or meta["status"] == status_filter):
            out.append(meta)
    return out


def delete(workflow_id: str) -> None:
    import shutil
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
