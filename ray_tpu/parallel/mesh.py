"""Device meshes: the TPU-native replacement for process groups.

Where the reference wires NCCL process groups per strategy (DP via torch
DDP in train/torch/config.py:115, TP/PP orchestrated for external libs,
collective groups in util/collective), the TPU build has ONE primitive: a
`jax.sharding.Mesh` over the chips with named logical axes, and XLA emits
the collectives.  This module owns mesh construction and axis conventions:

    dp    — pure data parallel (replicated params)
    fsdp  — data parallel with sharded params/opt-state (ZeRO-3 analog)
    tp    — tensor parallel (Megatron-style, intra-layer)
    sp    — sequence/context parallel (ring attention)
    ep    — expert parallel (MoE)
    pp    — pipeline stages (sub-meshes)

Multi-host: the same axis spec, built over jax.devices() after
jax.distributed.initialize — handled by parallel/mesh_group.py actors.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. -1 on at most one axis means 'fill with the
    remaining devices' (like a reshape wildcard)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed > n_devices:
            raise ValueError(
                f"mesh spec {sizes} needs {fixed} devices, have "
                f"{n_devices}")
        # fixed < n_devices: the mesh uses the first `fixed` devices (a
        # sub-mesh), matching how a job may claim part of a slice.
        return sizes


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None,
              axis_sizes: Optional[Dict[str, int]] = None):
    """Build a jax.sharding.Mesh.

    Device order matters for ICI locality: jax.devices() enumerates chips
    so that adjacent indices are ICI neighbors on a slice; we put the
    innermost (most communication-heavy) axes — tp, then sp — fastest-
    varying so their collectives ride ICI rings, and dp/pp outermost so
    cross-slice / DCN traffic lands there (scaling-book recipe; reference
    contrast: NCCL ranks are flat, ray.util.collective
    nccl_collective_group.py gives topology no meaning).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        spec = spec or MeshSpec(dp=-1)
        axis_sizes = spec.resolve(n)
    names = [a for a in AXIS_ORDER if axis_sizes.get(a, 1) > 1]
    if not names:
        names = ["dp"]
    shape = [axis_sizes.get(a, 1) for a in names]
    needed = math.prod(shape)
    if needed > n:
        raise ValueError(f"axis sizes {axis_sizes} need {needed} devices, "
                         f"have {n}")
    dev_array = np.asarray(devices[:needed]).reshape(shape)
    return Mesh(dev_array, names)


def mesh_axis_size(mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def sub_mesh_for_stage(mesh, stage: int):
    """Slice a pp-axis mesh into the per-stage sub-mesh (pipeline
    parallelism: each stage gets a contiguous block of devices)."""
    import jax
    from jax.sharding import Mesh

    if "pp" not in mesh.axis_names:
        raise ValueError("mesh has no pp axis")
    idx = mesh.axis_names.index("pp")
    dev = np.take(mesh.devices, stage, axis=idx)
    names = [a for a in mesh.axis_names if a != "pp"]
    return Mesh(dev, names)
