"""MeshGroup: gang-scheduled multi-host SPMD over a placement group.

The multi-host bring-up the reference gets from Train's backend executor
(python/ray/train/_internal/backend_executor.py:135 gang-spawns one
worker group per node, worker_group.py:102), rebuilt TPU-first:

  1. a placement group reserves one bundle per host (STRICT_SPREAD on a
     real cluster; PACK for single-machine simulation),
  2. one `_MeshHostWorker` actor is created per bundle,
  3. every worker calls `jax.distributed.initialize` (coordinator =
     rank 0), after which `jax.devices()` spans all hosts,
  4. `run(fn)` broadcasts an SPMD closure: each host executes the same
     program over the GLOBAL mesh, and XLA lays collectives over
     ICI/DCN.

This makes real the promise at parallel/mesh.py:17 ("handled by
parallel/mesh_group.py actors").
"""

from __future__ import annotations

import socket
from typing import Any, Callable, Dict, List, Optional, Sequence

import ray_tpu
from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)


class _MeshHostWorker:
    """One actor per host: owns that host's JAX runtime + local devices.

    Lives in its own worker process, so jax configuration (platform,
    device count, distributed init) is private to the gang.
    """

    def __init__(self, rank: int, world: int, platform: str,
                 local_devices: int) -> None:
        self.rank = rank
        self.world = world
        if platform == "cpu":
            n = max(local_devices, 1)
            # XLA_FLAGS first: it is read at backend init, so it works
            # on every jax version as long as this process has not
            # touched devices yet (a fresh gang worker has not).
            import os
            import re
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
            import jax
            jax.config.update("jax_platforms", "cpu")
            try:
                jax.config.update("jax_num_cpu_devices", n)
            except AttributeError:
                # jax < 0.5 has no jax_num_cpu_devices option; the
                # XLA_FLAGS override above provides the device count.
                pass
            if world > 1:
                try:
                    # Multi-host CPU collectives need gloo on jax
                    # 0.4.x ("Multiprocess computations aren't
                    # implemented on the CPU backend" otherwise).
                    # World-1 gangs (elastic shrink floor) must NOT
                    # set it: gloo requires a distributed client, and
                    # a single host never calls
                    # jax.distributed.initialize.
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except AttributeError:
                    pass  # newer jax selects CPU collectives itself

    def choose_coordinator(self) -> str:
        """Rank 0 picks the coordinator address ON ITS OWN HOST — the
        jax coordinator service binds in rank 0's process, so the
        address must be this machine's, not the driver's."""
        ip = _local_ip()
        return f"{ip}:{_free_port(ip)}"

    def setup(self, coordinator: str) -> int:
        """Join the gang; returns once every rank has connected."""
        import jax
        if self.world > 1:
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=self.world,
                                       process_id=self.rank)
        return self.rank

    def device_counts(self) -> Dict[str, int]:
        import jax
        return {"local": jax.local_device_count(),
                "global": jax.device_count(), "rank": self.rank}

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Execute fn(rank, *args, **kwargs) in this host's process.
        fn sees the multi-host JAX runtime (global jax.devices())."""
        return fn(self.rank, *args, **kwargs)

    def ping(self) -> int:
        return self.rank


def _local_ip() -> str:
    """This machine's reachable IP (UDP connect() sends no packets)."""
    try:
        # Context manager: an unroutable host raising mid-probe must
        # not leak the socket until GC (RT013 self-finding).
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class MeshGroup:
    """A gang of per-host JAX runtimes forming one global device mesh.

    Usage:
        mg = MeshGroup(num_hosts=2, devices_per_host=4)   # CPU simulate
        counts = mg.device_counts()      # every host sees global=8
        results = mg.run(train_fn, cfg)  # SPMD: same fn on every host
        mg.shutdown()
    """

    def __init__(self, num_hosts: int,
                 devices_per_host: int = 0,
                 platform: str = "cpu",
                 resources_per_host: Optional[Dict[str, float]] = None,
                 strategy: str = "PACK",
                 name: Optional[str] = None,
                 slice_type: Optional[str] = None,
                 pg_timeout_s: float = 60.0) -> None:
        if platform not in ("cpu", "tpu"):
            raise ValueError("platform must be 'cpu' or 'tpu'")
        self.num_hosts = num_hosts
        if slice_type is not None:
            # Gang the group onto ONE whole TPU slice: tpu_slice_bundles
            # marks bundle 0 with the TPU-<type>-head resource, which is
            # both the one-gang-per-slice exclusivity claim and the
            # demand signal a slice-provider autoscaler provisions from
            # (autoscaler/autoscaler.py TPU-head gang path).
            from ray_tpu.util.placement_group import tpu_slice_bundles
            bundles = tpu_slice_bundles(
                slice_type, num_hosts,
                chips_per_host=devices_per_host or 4)
            res = dict(bundles[1] if num_hosts > 1 else bundles[0])
            # One rank per host is the gang's whole point: PACK would
            # happily co-locate two bundles on one host (only bundle 0
            # carries the slice-head pin), splitting the ICI ring.
            strategy = "STRICT_SPREAD"
        else:
            res = dict(resources_per_host
                       or ({"CPU": 1} if platform == "cpu"
                           else {"TPU": float(devices_per_host or 4)}))
            bundles = [dict(res) for _ in range(num_hosts)]
        self.pg: PlacementGroup = placement_group(
            bundles, strategy=strategy, name=name)
        if not self.pg.wait(timeout_seconds=pg_timeout_s):
            remove_placement_group(self.pg)
            raise TimeoutError(
                f"MeshGroup placement group ({num_hosts} x {res}, "
                f"{strategy}) did not become ready")
        self._res = res
        self._platform = platform
        self._devices_per_host = devices_per_host
        self.restarts = 0
        # The PG was sized for num_hosts bundles; resize() can shrink
        # below and grow back up to this, never beyond.
        self.max_hosts = num_hosts
        self.resizes = 0
        self._spawn_gang()

    def _spawn_gang(self) -> None:
        cls = ray_tpu.remote(_MeshHostWorker)
        res, platform = self._res, self._platform
        tpus = res.get("TPU", 0) if platform == "tpu" else 0
        self.workers = [
            cls.options(num_cpus=res.get("CPU", 0), num_tpus=tpus,
                        placement_group=self.pg,
                        placement_group_bundle_index=i).remote(
                rank=i, world=self.num_hosts, platform=platform,
                local_devices=self._devices_per_host)
            for i in range(self.num_hosts)
        ]
        # Rank 0 picks the coordinator address on ITS host (which may
        # not be the driver's machine), then every rank joins — setup
        # is a barrier: jax.distributed.initialize returns only once
        # all ranks have connected.
        coordinator = ray_tpu.get(
            self.workers[0].choose_coordinator.remote(), timeout=120)
        ray_tpu.get([w.setup.remote(coordinator) for w in self.workers],
                    timeout=300)

    # -- elasticity (reference: backend_executor.py restart paths) ------
    def rebuild(self, retry_timeout_s: float = 180.0) -> None:
        """Tear down and re-rendezvous the whole gang.  One dead member
        poisons jax.distributed for everyone (the survivors hang in
        collectives against the dead peer), so recovery is always
        all-ranks: kill, respawn on the SAME placement-group bundles,
        re-initialize.

        The respawn retries: when the gang died WITH its nodes (slice
        preemption), actor creation races node-death detection and PG
        repair — the bundle map may still point at dead nodes for a few
        heartbeats, and replacement nodes may still be provisioning."""
        import time as _time
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.restarts += 1
        deadline = _time.monotonic() + retry_timeout_s
        while True:
            try:
                self._spawn_gang()
                return
            except Exception:
                for w in getattr(self, "workers", []):
                    try:
                        ray_tpu.kill(w)
                    except Exception:
                        pass
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(1.0)

    def resize(self, new_num_hosts: int,
               retry_timeout_s: float = 180.0) -> None:
        """Re-rendezvous the gang at a DIFFERENT world size on the
        same placement group (elastic shrink on preemption / grow-back
        on heal — the train/elastic.py resize, at the mesh layer).

        jax.distributed world membership is fixed at initialize(), so
        a resize is necessarily a full re-rendezvous: kill all ranks,
        respawn ``new_num_hosts`` of them on the first bundles, and
        re-initialize with the new world size.  State survival is the
        caller's job (reshard from an in-cluster checkpoint — the
        TpuTrainer elastic path — or re-load from disk).  Grow is
        bounded by ``max_hosts``: the placement group reserved exactly
        that many bundles at construction."""
        if not 1 <= new_num_hosts <= self.max_hosts:
            raise ValueError(
                f"new_num_hosts {new_num_hosts} not in "
                f"[1, {self.max_hosts}] (the placement group has "
                f"{self.max_hosts} bundles)")
        import time as _time
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.num_hosts = new_num_hosts
        self.resizes += 1
        deadline = _time.monotonic() + retry_timeout_s
        while True:
            try:
                self._spawn_gang()
                return
            except Exception:
                for w in getattr(self, "workers", []):
                    try:
                        ray_tpu.kill(w)
                    except Exception:
                        pass
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(1.0)

    def run_elastic(self, fn: Callable, *args,
                    max_restarts: int = 2,
                    timeout: Optional[float] = None,
                    **kwargs) -> List[Any]:
        """run(), surviving gang-member death: on a worker failure the
        gang is rebuilt and fn re-runs from scratch on every rank — fn
        must be resumable (load its latest checkpoint at start), the
        TpuTrainer/orbax pattern.  Reference:
        train/_internal/backend_executor.py worker-group restart +
        FailureConfig."""
        import time as _time
        from ray_tpu import exceptions as exc
        attempt = 0
        while True:
            refs = [w.run.remote(fn, *args, **kwargs)
                    for w in self.workers]
            deadline = (None if timeout is None
                        else _time.monotonic() + timeout)
            failure: Optional[BaseException] = None
            checked: set = set()
            while True:
                # Poll instead of one blocking get: a dead rank leaves
                # the survivors HUNG in collectives, so their refs
                # never resolve — the dead rank's error must be
                # noticed while the others are still pending.
                done, not_done = ray_tpu.wait(
                    refs, num_returns=len(refs), timeout=1.0)
                for r in done:
                    if r.binary() in checked:
                        continue
                    checked.add(r.binary())
                    try:
                        ray_tpu.get(r)
                    except BaseException as e:   # noqa: BLE001
                        failure = e
                        break
                if failure is not None or not not_done:
                    break
                if deadline is not None and _time.monotonic() > deadline:
                    # Survivors may be hung in collectives: a leaked
                    # gang is unusable, so tear it down before raising.
                    self.rebuild()
                    raise TimeoutError(
                        f"run_elastic timed out after {timeout}s")
            if failure is None:
                return ray_tpu.get(refs)
            worker_death = isinstance(
                failure, (exc.ActorDiedError, exc.WorkerCrashedError,
                          exc.ActorUnavailableError))
            if not worker_death or attempt >= max_restarts:
                # Application error (or restart budget exhausted): the
                # other ranks are hung against the failed peer — kill
                # and respawn the gang so the MeshGroup stays usable,
                # then surface the error.
                self.rebuild()
                raise failure
            attempt += 1
            self.rebuild()

    def device_counts(self) -> List[Dict[str, int]]:
        return ray_tpu.get(
            [w.device_counts.remote() for w in self.workers], timeout=60)

    def run(self, fn: Callable, *args, timeout: Optional[float] = None,
            **kwargs) -> List[Any]:
        """Run fn(rank, *args, **kwargs) on EVERY host concurrently
        (SPMD: all ranks must execute the same jitted programs).
        Returns per-rank results ordered by rank."""
        refs = [w.run.remote(fn, *args, **kwargs) for w in self.workers]
        return ray_tpu.get(refs, timeout=timeout)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
