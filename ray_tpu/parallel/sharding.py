"""Logical-axis sharding rules (GSPMD style).

The reference delegates sharded-weights strategies to torch FSDP /
DeepSpeed inside the worker loop (train/torch/train_loop_utils.py
prepare_model); here sharding is first-class: every parameter and
activation carries *logical* axis names, and a rule table maps logical
axes to mesh axes.  Changing parallelism = changing the rule table, never
the model code (the maxtext/scaling-book recipe).

Standard logical axes: "batch", "seq", "embed", "heads", "kv_heads",
"head_dim", "mlp", "vocab", "expert", "layers".
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# rule: logical axis -> mesh axis | tuple of mesh axes | None (replicated)
Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Batch is split over every data-ish axis; fsdp additionally shards the
# weights' embed dim (ZeRO-3); tp shards heads/mlp/vocab (Megatron).
DEFAULT_RULES: Rules = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    "layers": None,
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Rules] = None,
             mesh: Optional[Mesh] = None) -> P:
    """PartitionSpec for a tensor's logical axes, dropping mesh axes the
    mesh doesn't have (so one rule table serves every mesh shape)."""
    rules = rules if rules is not None else DEFAULT_RULES
    have = set(mesh.axis_names) if mesh is not None else None
    used = set()
    out = []
    for ax in logical_axes:
        m = rules.get(ax) if ax is not None else None
        if m is None:
            out.append(None)
            continue
        parts = (m,) if isinstance(m, str) else tuple(m)
        parts = tuple(p for p in parts
                      if (have is None or p in have) and p not in used)
        used.update(parts)
        if not parts:
            out.append(None)
        elif len(parts) == 1:
            out.append(parts[0])
        else:
            out.append(parts)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def tree_specs(logical_tree: Any, rules: Optional[Rules] = None,
               mesh: Optional[Mesh] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: spec_for(axes, rules, mesh),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x))


def tree_shardings(logical_tree: Any, mesh: Mesh,
                   rules: Optional[Rules] = None) -> Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        tree_specs(logical_tree, rules, mesh),
                        is_leaf=lambda x: isinstance(x, P))


def constrain(x, logical_axes: Sequence[Optional[str]],
              rules: Optional[Rules] = None,
              mesh: Optional[Mesh] = None):
    """Sharding constraint by logical names (inside jit)."""
    mesh = mesh or _current_mesh()
    if mesh is None or _mesh_trivial(mesh):
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec_for(logical_axes, rules, mesh)))


def _mesh_trivial(mesh: Mesh) -> bool:
    import math
    return math.prod(mesh.shape.values()) == 1


_MESH_STACK = []


class use_mesh:
    """Context manager setting the ambient mesh for `constrain`."""

    def __init__(self, mesh: Mesh) -> None:
        self.mesh = mesh

    def __enter__(self):
        _MESH_STACK.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _MESH_STACK.pop()


def _current_mesh() -> Optional[Mesh]:
    return _MESH_STACK[-1] if _MESH_STACK else None
