"""Pipeline parallelism over the `pp` mesh axis, inside ONE program.

The reference pipelines by orchestrating stage processes and p2p NCCL
sends between them; the TPU-native design keeps the whole GPipe
schedule INSIDE one jitted SPMD program: `shard_map` over the `pp`
axis gives every device its stage's layer stack, microbatch activations
hop stages with `lax.ppermute` (ICI neighbor exchange), and — because
ppermute is differentiable (its transpose is the reverse permute) — the
backward pass is just jax.grad through the schedule: XLA derives the
reverse pipeline instead of a hand-written 1F1B runtime.

Scaling-book recipe; reference contrast: torch pipeline engines
(orchestrated-only per SURVEY §2.3) with explicit send/recv ops.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
try:
    from jax import shard_map           # jax >= 0.8
except ImportError:                     # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def split_stages(layer_params, num_stages: int):
    """Reshape stacked layer params [L, ...] -> [num_stages, L/ps, ...]
    so the leading axis shards over `pp`."""
    def r(x):
        L = x.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers not divisible by "
                             f"{num_stages} stages")
        return x.reshape(num_stages, L // num_stages, *x.shape[1:])

    return jax.tree.map(r, layer_params)


def pipeline_apply(stage_params, x, mesh, layer_fn: Callable,
                   num_microbatches: int):
    """GPipe forward over the mesh's `pp` axis.

    stage_params: pytree with leading axes [num_stages, layers_per_stage,
    ...] (from split_stages).  x: [B, S, D] activations.  layer_fn(x, p)
    applies ONE layer.  Returns [B, S, D] after all layers.

    Differentiable end-to-end: wrap in jax.grad for pipelined training.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        def apply_all(x, sp):
            def scan_fn(h, p):
                return layer_fn(h, p), None
            flat = jax.tree.map(
                lambda a: a.reshape(-1, *a.shape[2:]), sp)
            h, _ = jax.lax.scan(scan_fn, x, flat)
            return h
        return apply_all(x, stage_params)

    B, S, D = x.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    x_mb = x.reshape(M, mb, S, D)

    # The microbatch's token dim shards over the data axes, so pp
    # composes with dp/fsdp instead of replicating the full batch
    # through every stage.
    data_axes = tuple(a for a in ("dp", "fsdp")
                      if mesh.shape.get(a, 1) > 1)
    xspec = P(None, data_axes if data_axes else None)

    def device_fn(sp, xm):
        # sp: this stage's layers [1, lps, ...]; xm: [M, mb/dp, S, D]
        sp = jax.tree.map(lambda a: a[0], sp)
        stage = jax.lax.axis_index("pp")
        mb_l = xm.shape[1]

        def apply_stage(h):
            def scan_fn(h, p):
                return layer_fn(h, p), None
            h, _ = jax.lax.scan(scan_fn, h, sp)
            return h

        state = jnp.zeros((mb_l, S, D), xm.dtype)
        outs = jnp.zeros((M, mb_l, S, D), xm.dtype)
        recv = state
        for t in range(M + pp - 1):
            # Stage 0 injects microbatch t (while any remain); others
            # consume what the previous stage just sent.
            inj = xm[min(t, M - 1)]
            state = apply_stage(jnp.where(stage == 0, inj, recv))
            # Collect finished microbatch t-(pp-1) from the last stage.
            oi = t - (pp - 1)
            if oi >= 0:
                outs = outs.at[oi].set(
                    jnp.where(stage == pp - 1, state, outs[oi]))
            recv = jax.lax.ppermute(
                state, "pp", [(i, i + 1) for i in range(pp - 1)])
        # Only the last stage holds real outputs: replicate via psum of
        # masked contributions.
        outs = jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, "pp")
        return outs

    try:
        fn = shard_map(
            device_fn, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stage_params),
                      xspec),
            out_specs=xspec,
            check_vma=False)
    except TypeError:   # jax < 0.7 spells check_vma as check_rep
        fn = shard_map(
            device_fn, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P("pp"), stage_params),
                      xspec),
            out_specs=xspec,
            check_rep=False)
    out = fn(stage_params, x_mb)
    return out.reshape(B, S, D)


def pipeline_forward_hidden(params: Dict[str, Any], tokens, cfg, mesh,
                            num_microbatches: int = 4):
    """Transformer forward_hidden with the layer stack pipelined over
    `pp` (embedding + final norm replicated on all stages)."""
    from ray_tpu.models import transformer as tf

    B, S = tokens.shape
    x = params["tok_embed"][tokens].astype(cfg.dtype)
    # [1, S]: broadcasts against any microbatch size inside the stages.
    positions = jnp.arange(S, dtype=jnp.int32)[None]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][:S][None].astype(cfg.dtype)

    pp = mesh.shape.get("pp", 1)
    stage_params = split_stages(params["layers"], pp)

    def layer_fn(h, p):
        h, _aux = tf._layer_body(cfg, None, h, p, positions)
        return h

    x = pipeline_apply(stage_params, x, mesh, layer_fn,
                       num_microbatches)
    rms = cfg.arch == "llama"
    return tf._norm(x, params["final_norm"],
                    params.get("final_norm_b"), cfg.norm_eps, rms)


def pipeline_loss_fn(params, tokens, cfg, mesh,
                     num_microbatches: int = 4):
    """Pipelined next-token loss; grads flow through the schedule."""
    from ray_tpu.models import transformer as tf
    targets = tokens[:, 1:]
    x = pipeline_forward_hidden(params, tokens[:, :-1], cfg, mesh,
                                num_microbatches)
    loss = tf.fused_cross_entropy(x, tf._w_out(params, cfg), targets,
                                  cfg)
    return loss
