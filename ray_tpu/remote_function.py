"""@remote functions.

Analog of the reference's python/ray/remote_function.py:40 (RemoteFunction,
_remote at :266): the decorator wraps a function; `.remote()` registers the
pickled function in the GCS function table once, then submits tasks that
reference it by id; `.options()` returns a shallow copy with overrides.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private.config import config
from ray_tpu._private.options import TASK_OPTIONS, validate_options

# Back-compat alias; the canonical table lives in _private/options.py
# (shared with actor.py and the RT003 lint rule).
_VALID_OPTIONS = TASK_OPTIONS


def _pg_spec_from_options(options: Dict[str, Any]) -> Optional[Dict]:
    pg = options.get("placement_group")
    if pg is None:
        return None
    index = options.get("placement_group_bundle_index", 0)
    # Fail fast at submission: an out-of-range bundle would otherwise
    # never match a reserved bundle and the task would pend forever.
    pg._check_bundle_index(index)
    return {"id": pg.id, "bundle": index}


def _retry_exceptions_from_options(options: Dict[str, Any]):
    """Normalize the `retry_exceptions` option: None/False (off), True
    (retry any application exception), or a tuple of QUALIFIED TYPE
    NAMES ("module.QualName").  Names, not classes: the task spec rides
    plain pickle, and a driver-__main__-defined exception class would
    fail to unpickle in the worker's receive loop (killing the worker
    instead of enabling retry).  The worker matches names against the
    raised exception's MRO (worker_main._app_retryable).  Validated at
    decoration/option time so a bad value fails at the call site."""
    pol = options.get("retry_exceptions")
    if pol is None or pol is False:
        return None
    if pol is True:
        return True
    try:
        types = tuple(pol)
    except TypeError:
        raise TypeError(
            "retry_exceptions must be True or a list/tuple of "
            f"exception types, got {pol!r}") from None
    for t in types:
        if not (isinstance(t, type) and issubclass(t, BaseException)):
            raise TypeError(
                f"retry_exceptions entries must be exception types, "
                f"got {t!r}")
    # Both name forms per type: cloudpickle-reconstructed classes can
    # lose the "<locals>" qualname prefix, so a function-local
    # exception's driver-side qualname may not equal its worker-side
    # one — the plain module.name form bridges that.
    names = set()
    for t in types:
        names.add(f"{t.__module__}.{t.__qualname__}")
        names.add(f"{t.__module__}.{t.__name__}")
    return tuple(sorted(names)) or None


def _resources_from_options(options: Dict[str, Any],
                            default_cpus: float) -> Dict[str, float]:
    res = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    if num_cpus is not None:
        res["CPU"] = float(num_cpus)
    elif "CPU" not in res:   # resources={"CPU": x} must not be clobbered
        res["CPU"] = float(default_cpus)
    num_tpus = options.get("num_tpus")
    if num_tpus:
        res["TPU"] = float(num_tpus)
    return {k: v for k, v in res.items() if v}


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None) -> None:
        self._fn = fn
        self._options = dict(options or {})
        validate_options(self._options, TASK_OPTIONS, "task")
        _retry_exceptions_from_options(self._options)  # fail-fast check
        self._blob: Optional[bytes] = None
        self._function_id: Optional[bytes] = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called "
            "directly; use .remote().")

    def options(self, **overrides) -> "RemoteFunction":
        merged = {**self._options, **overrides}
        rf = RemoteFunction(self._fn, merged)
        rf._blob = self._blob  # function bytes are option-independent
        return rf

    def _ensure_registered(self, client) -> bytes:
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._fn)
        # register_function dedupes by content hash client- and GCS-side.
        self._function_id = client.register_function(self._blob)
        return self._function_id

    def remote(self, *args, **kwargs):
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import apply_to_options
        client = ray_tpu._ensure_connected()
        apply_to_options(self._options)
        fid = self._ensure_registered(client)
        num_returns = self._options.get("num_returns", 1)
        resources = _resources_from_options(
            self._options, config.task_default_num_cpus)
        if num_returns == "streaming":
            # Streaming generator task (reference: num_returns="streaming"
            # -> ObjectRefGenerator, core_worker streaming generators):
            # each yield registers immediately; the caller consumes items
            # while the task still runs.  Retries are disabled — a
            # partially-consumed replay would double-deliver items.
            from ray_tpu._private import runtime_env as rte
            from ray_tpu.object_ref import ObjectRefGenerator
            refs = client.submit_task(
                function_id=fid,
                name=(self._options.get("name")
                      or self._fn.__qualname__),
                args=args, kwargs=kwargs, num_returns=1,
                resources=resources, retries=0,
                pg=_pg_spec_from_options(self._options),
                runtime_env=rte.pack(self._options.get("runtime_env")),
                affinity=self._options.get("_affinity"),
                actor_spec_extra={"streaming": True})
            return ObjectRefGenerator(refs[0], client)
        from ray_tpu._private import runtime_env as rte
        refs = client.submit_task(
            function_id=fid,
            name=self._options.get("name") or self._fn.__qualname__,
            args=args, kwargs=kwargs, num_returns=num_returns,
            resources=resources,
            retries=self._options.get("max_retries",
                                      config.max_task_retries),
            retry_exceptions=_retry_exceptions_from_options(
                self._options),
            pg=_pg_spec_from_options(self._options),
            runtime_env=rte.pack(self._options.get("runtime_env")),
            affinity=self._options.get("_affinity"))
        if num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a task-DAG node (reference: dag/function_node.py) —
        executed durably by ray_tpu.workflow.run.  Defined here (not
        monkey-patched at workflow import) so continuations returned
        from inside workers can bind too."""
        from ray_tpu.workflow import FunctionNode
        return FunctionNode(self, args, kwargs)

    def __reduce__(self):
        # Ship the underlying function + options.  The function is handed
        # to the OUTER pickler (not dumped eagerly) so its memo table can
        # break self-reference cycles (a recursive remote function's
        # closure contains this very wrapper).
        return (_rebuild_remote_function, (self._fn, self._options))


def _rebuild_remote_function(fn, options: Dict[str, Any]) -> RemoteFunction:
    return RemoteFunction(fn, options)
