"""In-process multi-node cluster fixture for tests and local experiments.

Analog of the reference's cluster_utils.Cluster
(python/ray/cluster_utils.py:135) — SURVEY §4 calls this the single
highest-leverage piece of test infrastructure.  The GCS server runs
in-process (threads); each added node is a real separate OS process
(`python -m ray_tpu._private.node_service`) with its own shm store,
worker pool, and TCP peer endpoints, so object transfer, spillback, and
node-death paths are exercised for real.

Usage:
    cluster = Cluster()
    cluster.add_node(resources={"remote": 1})
    ray_tpu.init(num_cpus=2, gcs_address=cluster.gcs_address)
    cluster.wait_for_nodes(2)            # head + 1
    ...
    ray_tpu.shutdown(); cluster.shutdown()
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional


def _drain(pipe) -> None:
    try:
        for _ in pipe:
            pass
    except (OSError, ValueError):
        pass


class NodeProc:
    def __init__(self, proc: subprocess.Popen, node_id: bytes) -> None:
        self.proc = proc
        self.node_id = node_id

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """Hard-kill the node process (node-death testing)."""
        try:
            os.kill(self.proc.pid, sig)
        except ProcessLookupError:
            pass
        self.proc.wait(timeout=10)


class Cluster:
    """One GCS (in-process) + N worker-node subprocesses."""

    def __init__(self, host: str = "127.0.0.1",
                 env: Optional[Dict[str, str]] = None,
                 persist_dir: Optional[str] = None) -> None:
        from ray_tpu._private.gcs_service import GcsServer
        self._server = GcsServer(host=host, persist_dir=persist_dir)
        self._server.start()
        self.host = host
        self.gcs_address = (host, self._server.port)
        self.nodes: List[NodeProc] = []
        self._env = dict(env or {})

    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 store_capacity: int = 0,
                 timeout_s: float = 30.0) -> NodeProc:
        env = dict(os.environ)
        env.update(self._env)
        # Node subprocesses never need a TPU backend of their own.
        env.setdefault("JAX_PLATFORMS", "cpu")
        # The ray_tpu package may live off sys.path (driver inserted it
        # manually); node subprocesses must still resolve it.
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        parts = [pkg_parent] + [p for p in sys.path if p and os.path.isdir(p)]
        for e in env.get("PYTHONPATH", "").split(os.pathsep):
            if e and e not in parts:
                parts.append(e)
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        cmd = [sys.executable, "-m", "ray_tpu._private.node_service",
               "--gcs-host", self.host,
               "--gcs-port", str(self.gcs_address[1]),
               "--resources", json.dumps(resources or {})]
        if store_capacity:
            cmd += ["--store-capacity", str(store_capacity)]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                text=True)
        deadline = time.time() + timeout_s
        node_id = b""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"node process exited (rc={proc.poll()})")
            if line.startswith("NODE_READY="):
                node_id = bytes.fromhex(line.strip().split("=", 1)[1])
                break
        if not node_id:
            proc.kill()
            raise TimeoutError("node did not come up")
        # Keep draining the pipe forever: the node's workers inherit this
        # stdout, and an undrained 64KB OS pipe buffer would block any
        # task that prints enough, deadlocking the cluster.
        threading.Thread(target=_drain, args=(proc.stdout,), daemon=True,
                         name="rtpu-node-stdout").start()
        node = NodeProc(proc, node_id)
        self.nodes.append(node)
        return node

    def wait_for_nodes(self, n: int, timeout_s: float = 30.0) -> None:
        """Block until the GCS reports n alive nodes."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if len(self._server.state.nodes(alive_only=True)) >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {n} nodes "
            f"(have {len(self._server.state.nodes(alive_only=True))})")

    def kill_node(self, node: NodeProc, sig: int = signal.SIGKILL) -> None:
        node.kill(sig)

    def drain_node(self, node: NodeProc, grace_s: float = 30.0,
                   wait: bool = True,
                   timeout_s: Optional[float] = None) -> None:
        """Gracefully remove a node: GCS-driven `node_draining` — the
        node hands back queued work, migrates actors, re-replicates
        sole object copies, then exits on its own.  The SIGTERM path
        (`kill_node(node, signal.SIGTERM)`) triggers the same drain
        from the node's signal handler (with its configured grace), so
        tests can exercise graceful vs. hard departure side by side
        next to the SIGKILL `kill_node` default."""
        self._server.state.drain_node(node.node_id, grace_s,
                                      "cluster_utils.drain_node")
        if wait:
            node.proc.wait(timeout=timeout_s or grace_s + 30.0)

    def shutdown(self) -> None:
        # Flip EVERY node to draining before the SIGTERMs: each node's
        # signal-handler drain then sees no healthy peer to replicate
        # objects or migrate actors to and exits promptly — a teardown
        # must not spend seconds copying state between dying nodes.
        draining = False
        for n in self.nodes:
            if n.proc.poll() is None:
                try:
                    draining |= self._server.state.drain_node(
                        n.node_id, 0.5, "cluster shutdown")
                except Exception:
                    pass
        if draining:
            # Let the node_draining pushes land before the SIGTERMs:
            # a TERM that beats its node's event would start a
            # default-grace sigterm drain against a cluster view where
            # peers still look alive.
            time.sleep(0.3)
        for n in self.nodes:
            if n.proc.poll() is None:
                n.proc.terminate()
        for n in self.nodes:
            try:
                n.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                n.proc.kill()
        self._server.shutdown()
