"""In-process multi-node cluster fixture for tests and local experiments.

Analog of the reference's cluster_utils.Cluster
(python/ray/cluster_utils.py:135) — SURVEY §4 calls this the single
highest-leverage piece of test infrastructure.  The GCS server runs
in-process (threads) by default, or as a real separate OS process with
``external_gcs=True``; each added node is a real separate OS process
(`python -m ray_tpu._private.node_service`) with its own shm store,
worker pool, and TCP peer endpoints, so object transfer, spillback, and
node-death paths are exercised for real.

GCS fault tolerance (ISSUE 7): with a ``persist_dir``, the control
plane survives ``kill_gcs()`` — SIGKILL for an external GCS, a cold
state-discarding teardown for the in-process one — and ``restart_gcs()``
brings a fresh server up on the SAME port recovering from WAL+snapshot,
so every node's GcsClient reconnects and re-syncs.  The seeded chaos
kind ``kill_gcs`` (site ``gcs``, ``down_s`` restart delay) drives the
same pair from a supervisor thread, replayably.

Usage:
    cluster = Cluster()
    cluster.add_node(resources={"remote": 1})
    ray_tpu.init(num_cpus=2, gcs_address=cluster.gcs_address)
    cluster.wait_for_nodes(2)            # head + 1
    ...
    ray_tpu.shutdown(); cluster.shutdown()
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional


def _drain(pipe) -> None:
    try:
        for _ in pipe:
            pass
    except (OSError, ValueError):
        pass


class NodeProc:
    def __init__(self, proc: subprocess.Popen, node_id: bytes) -> None:
        self.proc = proc
        self.node_id = node_id

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """Hard-kill the node process (node-death testing)."""
        try:
            os.kill(self.proc.pid, sig)
        except ProcessLookupError:
            pass
        self.proc.wait(timeout=10)


class Cluster:
    """One GCS (in-process or subprocess) + N worker-node subprocesses."""

    def __init__(self, host: str = "127.0.0.1",
                 env: Optional[Dict[str, str]] = None,
                 persist_dir: Optional[str] = None,
                 external_gcs: bool = False) -> None:
        self.host = host
        self._env = dict(env or {})
        self.external_gcs = external_gcs
        if external_gcs and persist_dir is None:
            # A subprocess GCS without persistence could never survive
            # kill_gcs — give it a scratch WAL dir by default.
            import tempfile
            persist_dir = tempfile.mkdtemp(prefix="rtpu_gcs_")
        self.persist_dir = persist_dir
        self._server = None
        self._gcs_proc: Optional[subprocess.Popen] = None
        self._gcs_client = None
        self._gcs_lock = threading.Lock()
        self._closing = False
        if external_gcs:
            self._gcs_port = self._spawn_gcs(port=0)
        else:
            from ray_tpu._private.gcs_service import GcsServer
            self._server = GcsServer(host=host, persist_dir=persist_dir)
            self._server.start()
            self._gcs_port = self._server.port
        self.gcs_address = (host, self._gcs_port)
        self.nodes: List[NodeProc] = []
        # Seeded chaos kind kill_gcs fires HERE: the fixture is the
        # GCS supervisor (the role a k8s restart policy or systemd
        # plays in production), so the kill + timed restart is driven
        # by the driver process's deterministic chaos schedule.
        threading.Thread(target=self._chaos_supervisor_loop, daemon=True,
                         name="rtpu-gcs-supervisor").start()

    # -- GCS lifecycle -----------------------------------------------------
    def _spawn_gcs(self, port: int) -> int:
        env = dict(os.environ)
        env.update(self._env)
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(
            [pkg_parent] + env.get("PYTHONPATH", "").split(os.pathsep)))
        cmd = [sys.executable, "-m", "ray_tpu._private.gcs_service",
               "--host", self.host, "--port", str(port),
               "--persist-dir", self.persist_dir]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                text=True)
        bound = 0
        deadline = time.time() + 30.0
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"GCS process exited (rc={proc.poll()})")
            if line.startswith("GCS_PORT="):
                bound = int(line.strip().split("=", 1)[1])
                break
        if not bound:
            proc.kill()
            raise TimeoutError("GCS process did not come up")
        threading.Thread(target=_drain, args=(proc.stdout,), daemon=True,
                         name="rtpu-gcs-stdout").start()
        self._gcs_proc = proc
        return bound

    def kill_gcs(self) -> None:
        """kill -9 the control plane.  External GCS: a literal SIGKILL.
        In-process GCS: the server is torn down and its state object
        DISCARDED, so a later restart_gcs() recovers exclusively from
        the WAL/snapshot — the same cold-restart semantics without the
        subprocess."""
        if self.persist_dir is None:
            raise RuntimeError(
                "kill_gcs without persist_dir would lose the cluster "
                "for good; construct Cluster(persist_dir=...)")
        # In-process teardown runs OUTSIDE the lock: blocking in here
        # convoyed every gcs_status() poller behind the shutdown (an
        # RT011 self-finding); restart_gcs's port-retry loop already
        # tolerates a server mid-teardown.  The external reap stays
        # UNDER the lock: restart_gcs's early-return relies on never
        # observing a SIGKILLed-but-unreaped child (poll() would still
        # be None and it would skip the respawn, leaving the control
        # plane down for good) — and reaping a SIGKILLed process is
        # prompt, so the convoy concern doesn't apply.
        server = None
        with self._gcs_lock:
            if self.external_gcs:
                proc = self._gcs_proc
                if proc is not None and proc.poll() is None:
                    os.kill(proc.pid, signal.SIGKILL)
                    proc.wait(timeout=10)  # ray-tpu: noqa[RT011]
            else:
                server, self._server = self._server, None
        if server is not None:
            server.shutdown()

    def restart_gcs(self) -> None:
        """Bring the GCS back on the SAME port, recovering hard state
        from the WAL/snapshot.  Nodes' GcsClients reconnect on their
        own and re-sync (epoch bump), rebuilding the soft state."""
        with self._gcs_lock:
            if self.external_gcs:
                if self._gcs_proc is not None \
                        and self._gcs_proc.poll() is None:
                    return
                self._spawn_gcs(port=self._gcs_port)
                return
            if self._server is not None:
                return
            from ray_tpu._private.gcs_service import GcsServer
            deadline = time.time() + 10.0
            while True:
                try:
                    self._server = GcsServer(host=self.host,
                                             port=self._gcs_port,
                                             persist_dir=self.persist_dir)
                    break
                except OSError:
                    if time.time() >= deadline:
                        raise
                    # Port-release retry must stay serialized vs a
                    # concurrent kill/restart — holding the lock
                    # through the backoff is the point.
                    time.sleep(0.1)  # ray-tpu: noqa[RT011]
            self._server.start()

    def _chaos_supervisor_loop(self) -> None:
        from ray_tpu._private.chaos import chaos
        while not self._closing:
            time.sleep(0.25)
            try:
                spec = chaos.fire_spec("gcs", "kill_gcs")
            except Exception:
                continue
            if spec is None:
                continue
            down = spec.get("down_s") or 1.0
            try:
                self.kill_gcs()
                time.sleep(down)
                if not self._closing:
                    self.restart_gcs()
            except Exception:
                pass

    # -- control-plane access (works across GCS restarts) ------------------
    def _state_client(self):
        """Reconnect-capable client for fixture-side control-plane
        queries (external mode; the in-process server is used
        directly)."""
        from ray_tpu._private.gcs_service import GcsClient
        with self._gcs_lock:
            if self._gcs_client is None:
                self._gcs_client = GcsClient(self.host, self._gcs_port)
            return self._gcs_client

    def gcs_nodes(self, alive_only: bool = True) -> List[dict]:
        if self._server is not None:
            return self._server.state.nodes(alive_only=alive_only)
        return self._state_client().nodes(alive_only=alive_only)

    def gcs_status(self) -> dict:
        """Epoch / uptime / WAL size card (see `ray_tpu gcs`)."""
        if self._server is not None:
            return self._server.state.status()
        return self._state_client().status()

    def _gcs_drain_node(self, node_id: bytes, grace_s: float,
                        reason: str) -> bool:
        if self._server is not None:
            return self._server.state.drain_node(node_id, grace_s, reason)
        return self._state_client().drain_node(node_id, grace_s, reason)

    # -- nodes -------------------------------------------------------------
    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 store_capacity: int = 0,
                 timeout_s: float = 30.0) -> NodeProc:
        env = dict(os.environ)
        env.update(self._env)
        # Node subprocesses never need a TPU backend of their own.
        env.setdefault("JAX_PLATFORMS", "cpu")
        # The ray_tpu package may live off sys.path (driver inserted it
        # manually); node subprocesses must still resolve it.
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        parts = [pkg_parent] + [p for p in sys.path if p and os.path.isdir(p)]
        for e in env.get("PYTHONPATH", "").split(os.pathsep):
            if e and e not in parts:
                parts.append(e)
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        cmd = [sys.executable, "-m", "ray_tpu._private.node_service",
               "--gcs-host", self.host,
               "--gcs-port", str(self.gcs_address[1]),
               "--resources", json.dumps(resources or {})]
        if store_capacity:
            cmd += ["--store-capacity", str(store_capacity)]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                text=True)
        deadline = time.time() + timeout_s
        node_id = b""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"node process exited (rc={proc.poll()})")
            if line.startswith("NODE_READY="):
                node_id = bytes.fromhex(line.strip().split("=", 1)[1])
                break
        if not node_id:
            proc.kill()
            raise TimeoutError("node did not come up")
        # Keep draining the pipe forever: the node's workers inherit this
        # stdout, and an undrained 64KB OS pipe buffer would block any
        # task that prints enough, deadlocking the cluster.
        threading.Thread(target=_drain, args=(proc.stdout,), daemon=True,
                         name="rtpu-node-stdout").start()
        node = NodeProc(proc, node_id)
        self.nodes.append(node)
        return node

    def wait_for_nodes(self, n: int, timeout_s: float = 30.0) -> None:
        """Block until the GCS reports n alive nodes."""
        deadline = time.time() + timeout_s
        count = 0
        while time.time() < deadline:
            try:
                count = len(self.gcs_nodes(alive_only=True))
            except Exception:
                count = 0       # GCS mid-restart: keep waiting
            if count >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {n} nodes (have {count})")

    def kill_node(self, node: NodeProc, sig: int = signal.SIGKILL) -> None:
        node.kill(sig)

    def drain_node(self, node: NodeProc, grace_s: float = 30.0,
                   wait: bool = True,
                   timeout_s: Optional[float] = None) -> None:
        """Gracefully remove a node: GCS-driven `node_draining` — the
        node hands back queued work, migrates actors, re-replicates
        sole object copies, then exits on its own.  The SIGTERM path
        (`kill_node(node, signal.SIGTERM)`) triggers the same drain
        from the node's signal handler (with its configured grace), so
        tests can exercise graceful vs. hard departure side by side
        next to the SIGKILL `kill_node` default."""
        self._gcs_drain_node(node.node_id, grace_s,
                             "cluster_utils.drain_node")
        if wait:
            node.proc.wait(timeout=timeout_s or grace_s + 30.0)

    def shutdown(self) -> None:
        self._closing = True
        # Flip EVERY node to draining before the SIGTERMs: each node's
        # signal-handler drain then sees no healthy peer to replicate
        # objects or migrate actors to and exits promptly — a teardown
        # must not spend seconds copying state between dying nodes.
        draining = False
        for n in self.nodes:
            if n.proc.poll() is None:
                try:
                    draining |= self._gcs_drain_node(
                        n.node_id, 0.5, "cluster shutdown")
                except Exception:
                    pass
        if draining:
            # Let the node_draining pushes land before the SIGTERMs:
            # a TERM that beats its node's event would start a
            # default-grace sigterm drain against a cluster view where
            # peers still look alive.
            time.sleep(0.3)
        for n in self.nodes:
            if n.proc.poll() is None:
                n.proc.terminate()
        for n in self.nodes:
            try:
                n.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                n.proc.kill()
        if self._gcs_client is not None:
            try:
                self._gcs_client.close()
            except Exception:
                pass
        if self._server is not None:
            self._server.shutdown()
        if self._gcs_proc is not None and self._gcs_proc.poll() is None:
            self._gcs_proc.terminate()
            try:
                self._gcs_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._gcs_proc.kill()
