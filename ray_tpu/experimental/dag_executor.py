"""Worker-side compiled-DAG execution loop.

Reference: python/ray/dag/compiled_dag_node.py `_execute_task` loops —
each participating actor runs one long-lived loop task that reads its
input channels, executes its ops in compiled order, and writes output
channels, until a channel is torn down.

`ops` wire format (built by ray_tpu.dag compile):
    [{"method": name,
      "ins":  [("chan", path) | ("local", key) | ("const", value)...],
      "kwargs": {k: ("const", value) | ("chan", path) | ("local", key)},
      "outs": [("chan", path) | ("local", key)...]}, ...]

Same-actor edges ride `local` (an in-process dict — zero IPC); only
cross-process edges pay a channel hop."""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.experimental.channel import Channel, ChannelClosed


def run_dag_loop(instance: Any, ops: List[dict]) -> int:
    chans: Dict[str, Channel] = {}

    def chan(path: str) -> Channel:
        c = chans.get(path)
        if c is None:
            c = Channel(path)
            chans[path] = c
        return c

    def resolve(slot, local):
        kind, v = slot
        if kind == "chan":
            return chan(v).read()
        if kind == "local":
            return local[v]
        return v

    ticks = 0
    try:
        while True:
            local: Dict[str, Any] = {}
            for op in ops:
                args = [resolve(s, local) for s in op["ins"]]
                kwargs = {k: resolve(s, local)
                          for k, s in (op.get("kwargs") or {}).items()}
                out = getattr(instance, op["method"])(*args, **kwargs)
                for kind, v in op["outs"]:
                    if kind == "chan":
                        chan(v).write(out)
                    else:
                        local[v] = out
            ticks += 1
    except ChannelClosed:
        return ticks
    finally:
        for c in chans.values():
            c.close()
