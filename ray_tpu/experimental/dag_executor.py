"""Worker-side compiled-DAG execution loop.

Reference: python/ray/dag/compiled_dag_node.py `_execute_task` loops —
each participating actor runs one long-lived loop task that reads its
input channels, executes its ops in compiled order, and writes output
channels, until a channel is torn down.

`ops` wire format (built by ray_tpu.dag compile):
    [{"method": name,
      "ins":  [("chan", path) | ("rchan_in", key) | ("local", key)
               | ("const", value)...],
      "kwargs": {k: slot},
      "outs": [("chan", path) | ("rchan_out", key, dst_hex)
               | ("local", key)...]},
     {"collective": {"op": "sum", "key": bytes, "rank": r, "world": n,
                     "nodes": [node_hex per rank]},
      "ins": [slot], "outs": [...]}, ...]

Same-actor edges ride `local` (an in-process dict — zero IPC);
same-node cross-process edges ride mmap `chan` rings (µs); cross-node
edges ride `rchan` — bounded queues on the consumer's node service,
fed over the persistent peer connections (the reference's
shared-memory/NCCL channel split, shared_memory_channel.py vs
torch_tensor_nccl_channel.py).

Collective ops (reference: dag/collective_node.py:134
CollectiveOutputNode) run a rank-0-rooted reduce over the rchan plane:
per-rank root in-queues keep ticks separated even when the DAG is
pipelined (each sender's per-queue order is FIFO)."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.experimental.channel import Channel, ChannelClosed
from ray_tpu.util.collective import _REDUCERS


def _run_collective(spec: dict, val: Any, client) -> Any:
    base: bytes = spec["key"]
    rank, world = spec["rank"], spec["world"]
    arr = np.asarray(val)
    if world == 1:
        return _REDUCERS[spec["op"]](np.stack([arr]))
    if rank == 0:
        parts = [arr]
        for r in range(1, world):
            parts.append(np.asarray(
                client.chan_recv(base + b"/in/%d" % r)))
        out = _REDUCERS[spec["op"]](np.stack(parts))
        for r in range(1, world):
            client.chan_send(bytes.fromhex(spec["nodes"][r]),
                             base + b"/out/%d" % r, out)
        return out
    client.chan_send(bytes.fromhex(spec["nodes"][0]),
                     base + b"/in/%d" % rank, arr)
    return np.asarray(client.chan_recv(base + b"/out/%d" % rank))


def run_dag_loop(instance: Any, ops: List[dict],
                 client: Optional[Any] = None) -> int:
    if client is None:
        from ray_tpu._private.client import get_global_client
        client = get_global_client()
    from ray_tpu.util.metrics import (DAG_HOP_BUCKETS,
                                      DAG_HOP_SECONDS_METRIC,
                                      shared_histogram)
    observe_hop = shared_histogram(
        DAG_HOP_SECONDS_METRIC,
        description="compiled-DAG per-edge hop duration",
        boundaries=DAG_HOP_BUCKETS,
        tag_keys=("edge",)).observer({"edge": "local"})
    chans: Dict[str, Channel] = {}

    def chan(path: str) -> Channel:
        c = chans.get(path)
        if c is None:
            c = Channel(path)
            chans[path] = c
        return c

    def resolve(slot, local):
        kind, *rest = slot
        if kind == "chan":
            return chan(rest[0]).read()
        if kind == "rchan_in":
            return client.chan_recv(rest[0])
        if kind == "local":
            return local[rest[0]]
        return rest[0]

    def emit(slot, out, local) -> None:
        kind, *rest = slot
        if kind == "chan":
            # Local hop = the sender-side mmap write (serialize into
            # the slot + publish, incl. any backpressure wait).  The
            # remote hop is observed node-side on the streamed edge.
            t0 = time.perf_counter()
            chan(rest[0]).write(out)
            observe_hop(time.perf_counter() - t0)
        elif kind == "rchan_out":
            client.chan_send(bytes.fromhex(rest[1]), rest[0], out)
        else:
            local[rest[0]] = out

    # Pre-bound tick plan: the per-tick loop is the hot path, so
    # method lookups and kwargs-shape checks happen once here, not
    # per item.
    plan = []
    for op in ops:
        method = (None if "collective" in op
                  else getattr(instance, op["method"]))
        plan.append((op.get("collective"), method, op["ins"],
                     list((op.get("kwargs") or {}).items()),
                     op["outs"]))

    ticks = 0
    try:
        while True:
            local: Dict[str, Any] = {}
            for coll, method, ins, kw_items, outs in plan:
                args = [resolve(s, local) for s in ins]
                if coll is not None:
                    out = _run_collective(coll, args[0], client)
                elif kw_items:
                    out = method(*args, **{k: resolve(s, local)
                                           for k, s in kw_items})
                else:
                    out = method(*args)
                for slot in outs:
                    emit(slot, out, local)
            ticks += 1
    except ChannelClosed:
        return ticks
    finally:
        for c in chans.values():
            c.close()
