"""Shared-memory SPSC channels: the compiled-graph transport.

Reference: python/ray/experimental/channel/shared_memory_channel.py
(Channel over mutable plasma buffers).  Our object store seals objects
immutably, so channels get their own primitive: an mmap'd /dev/shm ring
of fixed slots with single-producer/single-consumer semantics.

Layout (all 8-byte little-endian fields, 64-byte aligned header):

    [0]  capacity  (slots)
    [8]  slot_size (payload bytes per slot)
    [16] write_seq — published AFTER the slot payload is written
    [24] read_seq  — published AFTER the slot payload is consumed

A slot holds [8B length][payload].  On x86/ARM64 an aligned 8-byte
store is atomic and Python's mmap writes go straight to the shared
page, so publishing the sequence number AFTER the payload write is the
entire synchronization protocol (same design as the reference's
mutable-plasma seqlock).

Writes are zero-copy: the value serializes straight into the mmap slot
via SerializedObject.write_into (no intermediate bytes object), and
reads deserialize straight out of the mapped slot (out-of-band buffers
copied once, since the slot is recycled on release).

Oversized values don't raise: a payload larger than slot_size spills
into the shared-memory object store and the slot carries only the
16-byte object id (length field tagged with _SPILL).  The writer holds
the spilled ref until the reader's consumption is visible through
read_seq; the reader borrows it for the duration of the get.

Blocking uses adaptive spin -> sleep polling: pure spin for
`dag_spin_us` microseconds (µs-scale latency when hot), then escalating
sleeps (no burned core when cold)."""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Dict, Optional

from ray_tpu._private import serialization as ser
from ray_tpu._private.config import config
from ray_tpu.devtools import leaksan

_HEADER = 64
_Q = struct.Struct("<Q")

# Length-field flag: the slot holds a 16-byte object id of a spilled
# oversized payload, not an inline serialized value.
_SPILL = 1 << 63


class ChannelClosed(Exception):
    pass


class Channel:
    """One direction, one producer process, one consumer process."""

    def __init__(self, path: str, capacity: int = 8,
                 slot_size: int = 1 << 20, create: bool = False,
                 spin_us: Optional[int] = None) -> None:
        self.path = path
        self._created = create
        if create:
            size = _HEADER + capacity * (8 + slot_size)
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            except BaseException:
                # ftruncate/mmap failed (ENOSPC on /dev/shm): the
                # just-created file would otherwise survive as an
                # orphan no teardown knows about.
                try:
                    os.unlink(path)
                except OSError:
                    pass
                raise
            finally:
                os.close(fd)
            # Ledger: the creator owns the /dev/shm file until its
            # close(unlink=True) — a killed executor's channel file
            # shows up as a leaked channel_mmap.
            leaksan.register("channel_mmap", path)
            self._mm[0:8] = _Q.pack(capacity)
            self._mm[8:16] = _Q.pack(slot_size)
            self._mm[16:24] = _Q.pack(0)
            self._mm[24:32] = _Q.pack(0)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        self._view = memoryview(self._mm)
        self.capacity = _Q.unpack(self._mm[0:8])[0]
        self.slot_size = _Q.unpack(self._mm[8:16])[0]
        # Spin budget read once at construction (knob: dag_spin_us).
        self._spin_s = (config.dag_spin_us if spin_us is None
                        else spin_us) / 1e6
        # Writer-side refs of spilled oversized payloads, keyed by the
        # slot seq they rode in; pruned once read_seq moves past them.
        self._spilled: Dict[int, Any] = {}
        self._closed = False

    # -- seq accessors (aligned 8-byte torn-free reads/writes) ---------
    def _wseq(self) -> int:
        return _Q.unpack(self._mm[16:24])[0]

    def _rseq(self) -> int:
        return _Q.unpack(self._mm[24:32])[0]

    _CLOSED_SENTINEL = (1 << 64) - 1

    def _slot_off(self, seq: int) -> int:
        return _HEADER + (seq % self.capacity) * (8 + self.slot_size)

    def _wait(self, poll, timeout: Optional[float]) -> None:
        """Adaptive wait: pure spin for the dag_spin_us budget (the hot
        pipelined case is satisfied in a few iterations), then a
        sched_yield tier (~20ms: µs-scale wake-ups even when executors
        outnumber cores — a yielding waiter hands its core straight to
        the producer), then sleep 0.1ms escalating to 1ms (the
        futex-style cold path: no burned core)."""
        if poll():
            return
        now = time.monotonic()
        deadline = None if timeout is None else now + timeout
        spin_until = now + self._spin_s
        yield_until = spin_until + 0.020
        sleep_until = yield_until + 0.050
        while not poll():
            now = time.monotonic()
            if now < spin_until:
                continue
            if deadline is not None and now > deadline:
                raise TimeoutError("channel wait timed out")
            if now < yield_until:
                os.sched_yield()
            else:
                time.sleep(0.0001 if now < sleep_until else 0.001)

    # -- API -----------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        s = ser.serialize(value)
        if s.total_size > self.slot_size:
            self._write_spilled(value, timeout)
            return
        self._wait(lambda: (self._rseq() == self._CLOSED_SENTINEL
                            or self._wseq() - self._rseq()
                            < self.capacity), timeout)
        if self._rseq() == self._CLOSED_SENTINEL:
            raise ChannelClosed(self.path)
        seq = self._wseq()
        off = self._slot_off(seq)
        self._mm[off:off + 8] = _Q.pack(s.total_size)
        # Zero-copy: serialize straight into the mapped slot.
        s.write_into(self._view[off + 8:off + 8 + s.total_size])
        self._prune_spilled()
        self._mm[16:24] = _Q.pack(seq + 1)      # publish

    def _write_spilled(self, value: Any, timeout: Optional[float]) -> None:
        """Oversized payload: store the value in the shm object store
        and ship only its 16-byte id through the slot.  The writer's
        ref keeps the object alive until read_seq proves consumption."""
        from ray_tpu._private.client import get_global_client
        client = get_global_client()
        if client is None:
            raise ValueError(
                f"value exceeds channel slot_size {self.slot_size}B and "
                f"no runtime is connected to spill it to the object "
                f"store (pass a larger buffer_size_bytes at compile "
                f"time, or ray_tpu.init() first)")
        ref = client.put(value)
        oid = ref.binary()
        self._wait(lambda: (self._rseq() == self._CLOSED_SENTINEL
                            or self._wseq() - self._rseq()
                            < self.capacity), timeout)
        if self._rseq() == self._CLOSED_SENTINEL:
            raise ChannelClosed(self.path)
        seq = self._wseq()
        off = self._slot_off(seq)
        self._mm[off:off + 8] = _Q.pack(_SPILL | len(oid))
        self._mm[off + 8:off + 8 + len(oid)] = oid
        self._spilled[seq] = ref
        self._prune_spilled()
        self._mm[16:24] = _Q.pack(seq + 1)      # publish

    def _prune_spilled(self) -> None:
        """Drop spilled-payload refs whose slot the reader has moved
        past.  The reader borrows the object (add_ref ordered before
        its get on the same connection) BEFORE advancing read_seq, so
        this release can never race the consumption."""
        if not self._spilled:
            return
        rseq = self._rseq()
        if rseq == self._CLOSED_SENTINEL:
            self._spilled.clear()
            return
        for seq in [s for s in self._spilled if s < rseq]:
            del self._spilled[seq]

    def read(self, timeout: Optional[float] = None) -> Any:
        self._wait(lambda: (self._wseq() == self._CLOSED_SENTINEL
                            or self._wseq() > self._rseq()), timeout)
        if self._wseq() == self._CLOSED_SENTINEL:
            raise ChannelClosed(self.path)
        seq = self._rseq()
        off = self._slot_off(seq)
        n = _Q.unpack(self._mm[off:off + 8])[0]
        if n & _SPILL:
            value = self._read_spilled(
                bytes(self._mm[off + 8:off + 8 + (n & ~_SPILL)]))
        else:
            # Deserialize straight from the mapped slot; out-of-band
            # buffers are copied (the slot is recycled on release).
            value = ser.deserialize(self._view[off + 8:off + 8 + n],
                                    copy_buffers=True)
        self._mm[24:32] = _Q.pack(seq + 1)      # release slot
        return value

    @staticmethod
    def _read_spilled(oid: bytes) -> Any:
        from ray_tpu._private.client import get_global_client
        from ray_tpu.object_ref import ObjectRef
        client = get_global_client()
        if client is None:
            raise ChannelClosed(
                "spilled channel payload with no connected runtime")
        # Borrowed ref: the add_ref notify is ordered before the get on
        # this connection, so the writer-side release (after read_seq
        # advances) can never free the object first.
        ref = ObjectRef._from_wire(oid)
        return client.get([ref])[0]

    def close(self, unlink: bool = False) -> None:
        """Mark closed for the peer (poison both seqs), then unmap."""
        if self._closed:
            return
        self._closed = True
        self._spilled.clear()
        try:
            self._mm[16:24] = _Q.pack(self._CLOSED_SENTINEL)
            self._mm[24:32] = _Q.pack(self._CLOSED_SENTINEL)
            self._view.release()
            self._mm.flush()
            self._mm.close()
        except (ValueError, OSError, BufferError):
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass
        if self._created and (unlink or not os.path.exists(self.path)):
            leaksan.discharge("channel_mmap", self.path, expect=False)
