"""Shared-memory SPSC channels: the compiled-graph transport.

Reference: python/ray/experimental/channel/shared_memory_channel.py
(Channel over mutable plasma buffers).  Our object store seals objects
immutably, so channels get their own primitive: an mmap'd /dev/shm ring
of fixed slots with single-producer/single-consumer semantics.

Layout (all 8-byte little-endian fields, 64-byte aligned header):

    [0]  capacity  (slots)
    [8]  slot_size (payload bytes per slot)
    [16] write_seq — published AFTER the slot payload is written
    [24] read_seq  — published AFTER the slot payload is consumed

A slot holds [8B length][payload].  On x86/ARM64 an aligned 8-byte
store is atomic and Python's mmap writes go straight to the shared
page, so publishing the sequence number AFTER the payload write is the
entire synchronization protocol (same design as the reference's
mutable-plasma seqlock).  Blocking uses adaptive spin→sleep polling:
µs-scale latency when hot, no burned core when cold."""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from ray_tpu._private import serialization as ser

_HEADER = 64
_Q = struct.Struct("<Q")


class ChannelClosed(Exception):
    pass


class Channel:
    """One direction, one producer process, one consumer process."""

    def __init__(self, path: str, capacity: int = 8,
                 slot_size: int = 1 << 20, create: bool = False) -> None:
        self.path = path
        if create:
            size = _HEADER + capacity * (8 + slot_size)
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            self._mm[0:8] = _Q.pack(capacity)
            self._mm[8:16] = _Q.pack(slot_size)
            self._mm[16:24] = _Q.pack(0)
            self._mm[24:32] = _Q.pack(0)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        self.capacity = _Q.unpack(self._mm[0:8])[0]
        self.slot_size = _Q.unpack(self._mm[8:16])[0]
        self._closed = False

    # -- seq accessors (aligned 8-byte torn-free reads/writes) ---------
    def _wseq(self) -> int:
        return _Q.unpack(self._mm[16:24])[0]

    def _rseq(self) -> int:
        return _Q.unpack(self._mm[24:32])[0]

    _CLOSED_SENTINEL = (1 << 64) - 1

    def _slot_off(self, seq: int) -> int:
        return _HEADER + (seq % self.capacity) * (8 + self.slot_size)

    @staticmethod
    def _wait(poll, timeout: Optional[float]) -> None:
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        spins = 0
        while not poll():
            spins += 1
            if spins < 200:          # hot path: pure spin, ~µs latency
                continue
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel wait timed out")
            time.sleep(0.0001 if spins < 2000 else 0.001)

    # -- API -----------------------------------------------------------
    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        blob = ser.dumps(value)
        if len(blob) > self.slot_size:
            raise ValueError(
                f"value of {len(blob)}B exceeds channel slot_size "
                f"{self.slot_size}B (pass a larger slot_size at "
                f"compile/creation time)")
        self._wait(lambda: (self._rseq() == self._CLOSED_SENTINEL
                            or self._wseq() - self._rseq()
                            < self.capacity), timeout)
        if self._rseq() == self._CLOSED_SENTINEL:
            raise ChannelClosed(self.path)
        seq = self._wseq()
        off = self._slot_off(seq)
        self._mm[off:off + 8] = _Q.pack(len(blob))
        self._mm[off + 8:off + 8 + len(blob)] = blob
        self._mm[16:24] = _Q.pack(seq + 1)      # publish

    def read(self, timeout: Optional[float] = None) -> Any:
        self._wait(lambda: (self._wseq() == self._CLOSED_SENTINEL
                            or self._wseq() > self._rseq()), timeout)
        if self._wseq() == self._CLOSED_SENTINEL:
            raise ChannelClosed(self.path)
        seq = self._rseq()
        off = self._slot_off(seq)
        n = _Q.unpack(self._mm[off:off + 8])[0]
        blob = bytes(self._mm[off + 8:off + 8 + n])
        self._mm[24:32] = _Q.pack(seq + 1)      # release slot
        return ser.loads(blob)

    def close(self, unlink: bool = False) -> None:
        """Mark closed for the peer (poison both seqs), then unmap."""
        if self._closed:
            return
        self._closed = True
        try:
            self._mm[16:24] = _Q.pack(self._CLOSED_SENTINEL)
            self._mm[24:32] = _Q.pack(self._CLOSED_SENTINEL)
            self._mm.flush()
            self._mm.close()
        except (ValueError, OSError):
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass
