"""Experimental subsystems (channel, compiled DAG plumbing)."""
