"""Weight-only int8 quantization for serving large models on one chip.

The BASELINE north star is Llama-8B-shape serving: 8B bf16 weights are
16 GB — exactly the v5e's HBM, leaving nothing for the KV cache.  Int8
with per-output-channel scales halves that (~8 GB + ~2 GB KV at 16
slots x 1k ctx), so the 8B shape fits a single chip with headroom.

Design (TPU-first):
  * ``QuantizedArray`` is a registered pytree node holding ``q`` (int8)
    and a broadcast-ready per-output-channel scale ``s`` (f32).  It
    exposes ``astype``/``__getitem__``/``.T`` — the only three ways
    model code touches weights — so the *unchanged* decode path
    (models/decoding.py) runs quantized: ``p["wq"].astype(h.dtype)``
    dequantizes in-register and XLA fuses the int8 load + convert +
    scale into the matmul's operand read.  HBM traffic (the decode
    bottleneck) halves; the MXU still sees bf16.
  * Scales sit on the non-contracted (output) axes, so accuracy follows
    the per-channel weight range, and for stacked per-layer weights the
    scale keeps the leading layer axis — ``lax.scan`` slices q and s
    together.
  * ``init_quantized_params`` builds random int8 weights *directly* on
    device (no f32 stage), so an 8B-shape engine can be stood up for
    benchmarking on a 16 GB chip that could never hold the f32 tree.

Reference contrast: the reference has no quantization of its own — it
serves quantized LLMs only by delegating to vLLM on GPU
(doc/source/serve/doc_code/vllm_example.py).  Here the serving engine
owns the weights, so quantization is a framework feature.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig


@jax.tree_util.register_pytree_node_class
class QuantizedArray:
    """int8 tensor + f32 per-output-channel scale, drop-in for weights.

    ``s`` has the same rank as ``q`` with size 1 on contracted axes, so
    ``q * s`` broadcasts to the dequantized tensor.
    """

    __slots__ = ("q", "s")

    def __init__(self, q, s):
        self.q, self.s = q, s

    # -- the three access patterns model code uses ----------------------
    def astype(self, dtype):
        """Dequantize. f32 multiply, then cast: one fused elementwise
        chain that XLA folds into the consuming matmul's operand load."""
        return (self.q.astype(jnp.float32) * self.s).astype(dtype)

    def __getitem__(self, idx):
        """Gather-then-dequantize (embedding lookups). Returns a plain
        f32 array; callers .astype() it like any other weight."""
        return self.q[idx].astype(jnp.float32) * self.s[idx]

    @property
    def T(self) -> "QuantizedArray":
        return QuantizedArray(self.q.T, self.s.T)

    # -- introspection used by num_params / checkpointing ---------------
    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def size(self):
        return self.q.size

    @property
    def dtype(self):
        return self.q.dtype

    def nbytes_total(self) -> int:
        return (self.q.size * self.q.dtype.itemsize
                + self.s.size * self.s.dtype.itemsize)

    def __repr__(self):
        return f"QuantizedArray(q={self.q.shape}, s={self.s.shape})"

    # -- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def quantize(w: jax.Array, contract_axes: Tuple[int, ...]
             ) -> QuantizedArray:
    """Symmetric per-output-channel int8 quantization.

    ``contract_axes`` are the axes the consuming matmul sums over (plus
    any stacked-layer axis is NOT included — scales keep it so scan can
    slice).  Scale = absmax/127 over those axes.
    """
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=contract_axes, keepdims=True)
    s = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QuantizedArray(q, s)


# Per-weight contracted axes, EXCLUDING the leading stacked-layer axis
# (handled by offset below).  Matches the einsums in transformer.py /
# decoding.py: e.g. wq [d,h,dh] contracts d; wo [h,dh,d] contracts h,dh.
_LAYER_CONTRACT = {
    "wq": (0,), "wk": (0,), "wv": (0,),
    "wo": (0, 1),
    "w_gate": (0,), "w_up": (0,),
    "w_down": (0,),
}
# MoE variants carry a leading expert axis [E, ...]:
_MOE_CONTRACT = {"w_gate": (1,), "w_up": (1,), "w_down": (1,)}


def quantize_params(params: Dict[str, Any], cfg: TransformerConfig,
                    ) -> Dict[str, Any]:
    """Quantize a full-precision parameter tree for serving.

    Matmul weights (attention + MLP projections, embeddings, lm_head)
    become QuantizedArray; norms/biases/router stay full precision.
    The returned tree feeds models/decoding.py unchanged.
    """
    moe = cfg.moe_experts > 0
    layers = dict(params["layers"])
    for name in _LAYER_CONTRACT:
        if name not in layers:
            continue
        axes = (_MOE_CONTRACT.get(name, _LAYER_CONTRACT[name])
                if moe and name in _MOE_CONTRACT
                else _LAYER_CONTRACT[name])
        # +1: stacked [L, ...] layer axis stays un-reduced so scan
        # slices q and s in step.
        layers[name] = quantize(layers[name],
                                tuple(a + 1 for a in axes))
    out = dict(params, layers=layers)
    # tok_embed [V, D]: per-row (vocab) scales — correct for the gather
    # AND, transposed, per-output-channel for the tied lm_head matmul.
    out["tok_embed"] = quantize(params["tok_embed"], (1,))
    if "lm_head" in params:   # [D, V] contracts D
        out["lm_head"] = quantize(params["lm_head"], (0,))
    return out


def _init_quantized_layer(cfg: TransformerConfig, key: jax.Array,
                          L: int) -> Dict[str, Any]:
    d, h, hkv, dh, f = (cfg.d_model, cfg.n_heads, cfg.kv_heads,
                        cfg.head_dim, cfg.ff_dim)
    scale_in = 1.0 / math.sqrt(d)
    scale_out = 1.0 / math.sqrt(d) / math.sqrt(2 * L)

    def rand_q(key, shape, scale, contract_axes):
        # int8 uniform in [-127, 127]; scale chosen so the dequantized
        # std ~ the init std (uniform/127 has std ~0.58).
        q = jax.random.randint(key, shape, -127, 128, jnp.int8)
        s_shape = tuple(1 if i in contract_axes else n
                        for i, n in enumerate(shape))
        s = jnp.full(s_shape, scale / 0.58 / 127.0, jnp.float32)
        return QuantizedArray(q, s)

    def layer_init(key):
        ks = jax.random.split(key, 8)
        p = {
            "attn_norm": jnp.ones((L, d), cfg.param_dtype),
            "wq": rand_q(ks[0], (L, d, h, dh), scale_in, (1,)),
            "wk": rand_q(ks[1], (L, d, hkv, dh), scale_in, (1,)),
            "wv": rand_q(ks[2], (L, d, hkv, dh), scale_in, (1,)),
            "wo": rand_q(ks[3], (L, h, dh, d), scale_out, (1, 2)),
            "mlp_norm": jnp.ones((L, d), cfg.param_dtype),
            "w_down": rand_q(ks[5], (L, f, d), scale_out, (1,)),
        }
        if cfg.arch == "llama":
            p["w_gate"] = rand_q(ks[4], (L, d, f), scale_in, (1,))
            p["w_up"] = rand_q(ks[6], (L, d, f), scale_in, (1,))
        else:
            p["w_up"] = rand_q(ks[6], (L, d, f), scale_in, (1,))
            p["b_up"] = jnp.zeros((L, f), cfg.param_dtype)
            p["b_down"] = jnp.zeros((L, d), cfg.param_dtype)
            p["attn_norm_b"] = jnp.zeros((L, d), cfg.param_dtype)
            p["mlp_norm_b"] = jnp.zeros((L, d), cfg.param_dtype)
        return p

    return layer_init(key)


def init_quantized_params(cfg: TransformerConfig,
                          key: jax.Array) -> Dict[str, Any]:
    """Random int8-quantized params, built WITHOUT an f32 stage.

    For standing up large-shape serving benchmarks: an 8B f32 tree is
    32 GB and can never exist on a 16 GB chip; this builds the int8
    tree (~8 GB for llama-8b) directly.  MoE shapes are for the train
    path only and are not supported here.
    """
    if cfg.moe_experts > 0:
        raise NotImplementedError("quantized serving is dense-only")
    keys = jax.random.split(key, 4)
    d = cfg.d_model
    params: Dict[str, Any] = {
        "tok_embed": QuantizedArray(
            jax.random.randint(keys[1], (cfg.vocab_size, d), -127, 128,
                               jnp.int8),
            jnp.full((cfg.vocab_size, 1), 1.0 / 0.58 / 127.0,
                     jnp.float32)),
        "layers": _init_quantized_layer(cfg, keys[0], cfg.n_layers),
        "final_norm": jnp.ones((d,), cfg.param_dtype),
    }
    if cfg.arch == "gpt2":
        params["pos_embed"] = (
            jax.random.normal(keys[2], (cfg.max_seq, d), jnp.float32)
            * 0.01).astype(cfg.param_dtype)
        params["final_norm_b"] = jnp.zeros((d,), cfg.param_dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = QuantizedArray(
            jax.random.randint(keys[3], (d, cfg.vocab_size), -127, 128,
                               jnp.int8),
            jnp.full((1, cfg.vocab_size),
                     (1.0 / math.sqrt(d)) / 0.58 / 127.0, jnp.float32))
    return params


def param_bytes(params) -> int:
    """Total parameter-tree bytes (counts q+s for QuantizedArray).
    Works on concrete arrays AND ShapeDtypeStructs (eval_shape)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * jnp.dtype(leaf.dtype).itemsize
    return total


def kv_cache_bytes(cfg: TransformerConfig, num_slots: int,
                   max_len: int) -> int:
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return (2 * cfg.n_layers * num_slots * max_len * cfg.kv_heads
            * cfg.head_dim * itemsize)


def serving_memory_report(cfg: TransformerConfig, num_slots: int,
                          max_len: int,
                          quantized: bool = True) -> Dict[str, Any]:
    """Shape-only HBM budget for a serving config (no allocation)."""
    init = init_quantized_params if quantized else None
    if quantized:
        tree = jax.eval_shape(
            lambda: init(cfg, jax.random.PRNGKey(0)))
    else:
        from ray_tpu.models.transformer import init_params
        tree = jax.eval_shape(
            lambda: init_params(cfg, jax.random.PRNGKey(0)))
        # served full-precision weights are cast to cfg.dtype once
        tree = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, cfg.dtype), tree)
    pb = param_bytes(tree)
    kb = kv_cache_bytes(cfg, num_slots, max_len)
    return {"param_gb": round(pb / 2**30, 2),
            "kv_cache_gb": round(kb / 2**30, 2),
            "total_gb": round((pb + kb) / 2**30, 2),
            "quantized": quantized,
            "num_slots": num_slots, "max_len": max_len}
