"""Decoder-only transformer family (Llama-style and GPT-2-style), pure JAX.

TPU-first design decisions:
  * Parameters are a plain pytree with a parallel tree of *logical axis
    names* (parallel/sharding.py) — pjit shards params/activations from
    rule tables; model code never mentions devices.
  * Layers run under `lax.scan` over stacked per-layer params: one
    compiled layer body regardless of depth (fast compiles, XLA-friendly).
  * bf16 activations/matmuls with f32 softmax/norm/logits; params f32.
  * Attention dispatches to the pallas flash kernel on TPU, the reference
    path elsewhere; with an `sp` mesh axis it uses ring attention.
  * `jax.checkpoint` (remat) around each layer trades FLOPs for HBM.

Reference contrast: the reference has no model zoo of its own (RLlib
models aside); Train wraps torch models.  This transformer is the
flagship workload for the Train/bench path (BASELINE.json configs 1-2).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.ad_checkpoint
import jax.numpy as jnp

from ray_tpu.ops.attention import attention, attention_with_lse
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None      # None => MHA
    d_ff: Optional[int] = None            # None => arch default
    max_seq: int = 2048
    arch: str = "llama"                   # "llama" | "gpt2"
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16             # activation/compute dtype
    param_dtype: Any = jnp.float32
    tie_embeddings: bool = False
    remat: bool = True
    remat_policy: str = "dots"            # dots | nothing
    attn_impl: str = "auto"               # auto | flash | reference
    attn_block_q: int = 512               # flash kernel tile sizes
    attn_block_k: int = 512
    # Fused cross-entropy chunk (tokens per logits block). None => dense
    # [B,S,V] logits path (only sensible for tiny vocab/testing).
    xent_chunk: Optional[int] = 1024
    # Mixture-of-Experts (expert-parallel over the `ep` mesh axis,
    # SURVEY §2.3 TPU-build obligation; reference analog: Mixtral-style
    # expert parallelism, BASELINE config #3).  0 => dense MLP.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.arch == "llama":
            # 8/3 * d rounded up to a 128 multiple: MXU-tile friendly and
            # divisible by any power-of-two tp degree.
            return ((int(self.d_model * 8 / 3) + 127) // 128) * 128
        return 4 * self.d_model


# -- presets (flagship + test) ----------------------------------------------
PRESETS: Dict[str, TransformerConfig] = {
    "tiny": TransformerConfig(vocab_size=512, d_model=128, n_layers=2,
                              n_heads=4, max_seq=256, remat=False),
    "gpt2-small": TransformerConfig(vocab_size=50_304, d_model=768,
                                    n_layers=12, n_heads=12, arch="gpt2",
                                    max_seq=1024, rope_theta=0.0),
    "llama-1b": TransformerConfig(vocab_size=128_256, d_model=2048,
                                  n_layers=16, n_heads=32, n_kv_heads=8,
                                  d_ff=8192, max_seq=8192),
    "llama-8b": TransformerConfig(vocab_size=128_256, d_model=4096,
                                  n_layers=32, n_heads=32, n_kv_heads=8,
                                  d_ff=14_336, max_seq=8192),
    # BASELINE.json config #3 ("Mixtral 8x7B MoE expert-parallel"):
    # Mixtral-shaped MoE — 8 experts, top-2 routing, expert-parallel
    # over the `ep` mesh axis.
    "mixtral-8x7b": TransformerConfig(vocab_size=32_000, d_model=4096,
                                      n_layers=32, n_heads=32,
                                      n_kv_heads=8, d_ff=14_336,
                                      max_seq=8192, moe_experts=8,
                                      moe_top_k=2),
}


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------
def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Returns the parameter pytree (per-layer params stacked on axis 0)."""
    keys = jax.random.split(key, 8)
    d, h, hkv, dh, f = (cfg.d_model, cfg.n_heads, cfg.kv_heads,
                        cfg.head_dim, cfg.ff_dim)
    L = cfg.n_layers
    pd = cfg.param_dtype

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale
                ).astype(pd)

    scale_in = 1.0 / math.sqrt(d)
    scale_out = 1.0 / math.sqrt(d) / math.sqrt(2 * L)

    def layer_init(key):
        ks = jax.random.split(key, 8)
        p = {
            "attn_norm": jnp.ones((d,), pd),
            "wq": normal(ks[0], (d, h, dh), scale_in),
            "wk": normal(ks[1], (d, hkv, dh), scale_in),
            "wv": normal(ks[2], (d, hkv, dh), scale_in),
            "wo": normal(ks[3], (h, dh, d), scale_out),
            "mlp_norm": jnp.ones((d,), pd),
            "w_down": normal(ks[5], (f, d), scale_out),
        }
        if cfg.moe_experts > 0:
            E = cfg.moe_experts
            p["w_router"] = normal(ks[7], (d, E), scale_in)
            p["w_gate"] = normal(ks[4], (E, d, f), scale_in)
            p["w_up"] = normal(ks[6], (E, d, f), scale_in)
            p["w_down"] = normal(ks[5], (E, f, d), scale_out)
            if cfg.arch == "gpt2":
                p["attn_norm_b"] = jnp.zeros((d,), pd)
                p["mlp_norm_b"] = jnp.zeros((d,), pd)
            return p
        if cfg.arch == "llama":
            p["w_gate"] = normal(ks[4], (d, f), scale_in)
            p["w_up"] = normal(ks[6], (d, f), scale_in)
        else:
            p["w_up"] = normal(ks[6], (d, f), scale_in)
            p["b_up"] = jnp.zeros((f,), pd)
            p["b_down"] = jnp.zeros((d,), pd)
            p["attn_norm_b"] = jnp.zeros((d,), pd)
            p["mlp_norm_b"] = jnp.zeros((d,), pd)
        return p

    layer_keys = jax.random.split(keys[0], L)
    layers = jax.vmap(layer_init)(layer_keys)

    params: Dict[str, Any] = {
        "tok_embed": normal(keys[1], (cfg.vocab_size, d), 1.0),
        "layers": layers,
        "final_norm": jnp.ones((d,), pd),
    }
    if cfg.arch == "gpt2":
        params["pos_embed"] = normal(keys[2], (cfg.max_seq, d), 0.01)
        params["final_norm_b"] = jnp.zeros((d,), pd)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(keys[3], (d, cfg.vocab_size), scale_in)
    return params


def logical_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Pytree (matching init_params) of logical axis-name tuples."""
    layer = {
        "attn_norm": ("embed",),
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
        "mlp_norm": ("embed",),
        "w_down": ("mlp", "embed"),
    }
    if cfg.moe_experts > 0:
        layer["w_router"] = ("embed", None)
        layer["w_gate"] = ("expert", "embed", "mlp")
        layer["w_up"] = ("expert", "embed", "mlp")
        layer["w_down"] = ("expert", "mlp", "embed")
        if cfg.arch == "gpt2":
            layer["attn_norm_b"] = ("embed",)
            layer["mlp_norm_b"] = ("embed",)
    elif cfg.arch == "llama":
        layer["w_gate"] = ("embed", "mlp")
        layer["w_up"] = ("embed", "mlp")
    else:
        layer["w_up"] = ("embed", "mlp")
        layer["b_up"] = ("mlp",)
        layer["b_down"] = ("embed",)
        layer["attn_norm_b"] = ("embed",)
        layer["mlp_norm_b"] = ("embed",)
    # stacked layer axis is the scan ("layers") axis
    layer = {k: ("layers",) + v for k, v in layer.items()}
    axes: Dict[str, Any] = {
        "tok_embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("embed",),
    }
    if cfg.arch == "gpt2":
        axes["pos_embed"] = (None, "embed")
        axes["final_norm_b"] = ("embed",)
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _norm(x, w, b, eps, rms: bool):
    xf = x.astype(jnp.float32)
    if rms:
        y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)
    else:
        mu = jnp.mean(xf, -1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), -1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def _rope(x, positions, theta):
    """x: [B, S, H, Dh]; rotary embedding over the head dim."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = jnp.exp(-math.log(theta) *
                    jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _moe_block(cfg: TransformerConfig, mesh, h, p):
    """Expert-parallel MoE FFN (GShard-style dense dispatch).

    h: [B, S, D] (already normed) -> ([B, S, D], aux_loss scalar).

    TPU-first formulation: routing is expressed as dense einsums with a
    fixed per-expert capacity; the expert dimension is sharded over the
    `ep` mesh axis (rules: "expert" -> ep), so XLA inserts the
    all-to-all between the token-sharded and expert-sharded layouts —
    the collective the reference would run through NCCL alltoall, here
    derived from sharding constraints and ridden over ICI.
    """
    B, S, D = h.shape
    E, K = cfg.moe_experts, cfg.moe_top_k
    T = B * S
    ht = h.reshape(T, D)
    logits = jnp.einsum("td,de->te", ht.astype(jnp.float32),
                        p["w_router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)               # [T, E]
    gate_vals, gate_idx = jax.lax.top_k(probs, K)         # [T, K]
    # Normalize the selected gates to sum 1 (Mixtral-style).
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    cap = int(math.ceil(T * K * cfg.moe_capacity_factor / E))
    combine = jnp.zeros((T, E, cap), jnp.float32)
    occupancy = jnp.zeros((T, E), jnp.float32)
    for j in range(K):
        onehot = jax.nn.one_hot(gate_idx[:, j], E)        # [T, E]
        pos = jnp.cumsum(onehot, axis=0) - onehot + \
            jnp.sum(occupancy, axis=0, keepdims=True)     # [T, E]
        pos_t = jnp.sum(pos * onehot, axis=-1)            # [T]
        keep = (pos_t < cap).astype(jnp.float32)
        slot = jax.nn.one_hot(pos_t.astype(jnp.int32), cap)
        combine = combine + (gate_vals[:, j] * keep)[:, None, None] \
            * onehot[:, :, None] * slot[:, None, :]
        occupancy = occupancy + onehot * keep[:, None]

    dispatch = (combine > 0).astype(cfg.dtype)            # [T, E, cap]
    xin = jnp.einsum("tec,td->ecd", dispatch, ht)         # [E, cap, D]
    xin = constrain(xin, ("expert", None, "embed"), mesh=mesh)
    wg = p["w_gate"].astype(cfg.dtype)
    wu = p["w_up"].astype(cfg.dtype)
    wd = p["w_down"].astype(cfg.dtype)
    gate = jnp.einsum("ecd,edf->ecf", xin, wg)
    up = jnp.einsum("ecd,edf->ecf", xin, wu)
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(cfg.dtype) * up
    act = constrain(act, ("expert", None, "mlp"), mesh=mesh)
    out_e = jnp.einsum("ecf,efd->ecd", act, wd)           # [E, cap, D]
    out = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), out_e)
    out = out.reshape(B, S, D)

    # Load-balancing auxiliary loss (Switch/GShard): fraction of tokens
    # per expert x mean router prob per expert, scaled by E.
    top1 = jax.nn.one_hot(gate_idx[:, 0], E)
    frac_tokens = jnp.mean(top1, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return out, aux


def _layer_body(cfg: TransformerConfig, mesh, x, p, positions):
    """One decoder layer. x: [B, S, D]."""
    rms = cfg.arch == "llama"
    h = _norm(x, p["attn_norm"], p.get("attn_norm_b"), cfg.norm_eps, rms)
    q = jnp.einsum("bsd,dhk->bshk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, p["wv"].astype(h.dtype))
    if cfg.arch == "llama":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    q = q.transpose(0, 2, 1, 3)   # [B, H, S, Dh]
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q = constrain(q, ("batch", "heads", "seq", None), mesh=mesh)
    k = constrain(k, ("batch", "kv_heads", "seq", None), mesh=mesh)
    v = constrain(v, ("batch", "kv_heads", "seq", None), mesh=mesh)
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        o = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        o = jax.ad_checkpoint.checkpoint_name(o, "attn_out")
    else:
        # Both outputs arrive tagged remat-saveable ("attn_out"/
        # "attn_lse") by the dispatcher/custom-vjp, so the dots policy
        # never re-runs the forward kernel in the backward pass; lse is
        # consumed only as a bwd residual.
        o, _ = attention_with_lse(q, k, v, causal=True,
                                  impl=cfg.attn_impl,
                                  block_q=cfg.attn_block_q,
                                  block_k=cfg.attn_block_k)
    o = o.transpose(0, 2, 1, 3)   # [B, S, H, Dh]
    attn_out = jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(o.dtype))
    x = x + constrain(attn_out, ("batch", "seq", "embed"), mesh=mesh)

    h = _norm(x, p["mlp_norm"], p.get("mlp_norm_b"), cfg.norm_eps, rms)
    if cfg.moe_experts > 0:
        moe_out, aux = _moe_block(cfg, mesh, h, p)
        x = x + constrain(moe_out, ("batch", "seq", "embed"), mesh=mesh)
        return x, aux
    if cfg.arch == "llama":
        gate = jnp.einsum("bsd,df->bsf", h, p["w_gate"].astype(h.dtype))
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"].astype(h.dtype))
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up
    else:
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"].astype(h.dtype))
        up = up + p["b_up"].astype(h.dtype)
        act = jax.nn.gelu(up.astype(jnp.float32)).astype(h.dtype)
    act = constrain(act, ("batch", "seq", "mlp"), mesh=mesh)
    down = jnp.einsum("bsf,fd->bsd", act, p["w_down"].astype(act.dtype))
    if cfg.arch == "gpt2":
        down = down + p["b_down"].astype(down.dtype)
    down = jax.ad_checkpoint.checkpoint_name(down, "ffn_out")
    x = x + constrain(down, ("batch", "seq", "embed"), mesh=mesh)
    return x, jnp.zeros((), jnp.float32)


def _remat_policy(cfg: TransformerConfig):
    if cfg.remat_policy == "nothing":
        return jax.checkpoint_policies.nothing_saveable
    if cfg.remat_policy == "names":
        # Save only the d_model-sized per-layer outputs; recompute the
        # d_ff-sized gate/up/act tensors (and qkv projections) in the
        # backward pass.  At d_ff=4*d this trades ~+12% step FLOPs for a
        # ~4x cut in saved-activation HBM vs "dots" — the policy that
        # lets ~1B-param configs train on a single 16 GB v5e chip.
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse", "ffn_out")
    # "dots": save matmul outputs (qkv/wo/mlp projections — no batch dims
    # in those dot_generals) plus the flash-attention output, so the bwd
    # pass recomputes only cheap elementwise/norm work.
    return jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse"))


def forward_hidden_aux(params: Dict[str, Any], tokens: jax.Array,
                       cfg: TransformerConfig, mesh=None
                       ) -> Tuple[jax.Array, jax.Array]:
    """tokens: [B, S] int32 -> (final-norm hidden [B, S, D],
    summed MoE aux loss — zero for dense models)."""
    B, S = tokens.shape
    # Shard the indices BEFORE the lookup: a replicated-index gather from
    # the (vocab/embed)-sharded table comes out embed-sharded, and moving
    # that to the (batch, seq)-sharded activation layout forces XLA into
    # involuntary full rematerialization (spmd_partitioner.cc:652).  With
    # (batch, seq)-sharded indices the gather lands directly in
    # activation layout and the table's shards are all-gathered once —
    # the same all-gather ZeRO-3 pays anyway when a weight is used.
    tokens = constrain(tokens, ("batch", "seq"), mesh=mesh)
    emb = constrain(params["tok_embed"], (None, None), mesh=mesh)
    x = emb[tokens].astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"), mesh=mesh)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][:S][None].astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"), mesh=mesh)

    body = functools.partial(_layer_body, cfg, mesh, positions=positions)
    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg))

    def scan_fn(carry, layer_params):
        x, aux = carry
        x, a = body(x, layer_params)
        return (x, aux + a), None

    (x, aux), _ = jax.lax.scan(
        scan_fn, (x, jnp.zeros((), jnp.float32)), params["layers"])

    rms = cfg.arch == "llama"
    return _norm(x, params["final_norm"], params.get("final_norm_b"),
                 cfg.norm_eps, rms), aux


def forward_hidden(params: Dict[str, Any], tokens: jax.Array,
                   cfg: TransformerConfig, mesh=None) -> jax.Array:
    """tokens: [B, S] int32 -> final-norm hidden states [B, S, D]."""
    return forward_hidden_aux(params, tokens, cfg, mesh)[0]


def _w_out(params, cfg: TransformerConfig):
    return (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])


def forward(params: Dict[str, Any], tokens: jax.Array,
            cfg: TransformerConfig, mesh=None) -> jax.Array:
    """tokens: [B, S] int32 -> logits [B, S, vocab] (f32)."""
    x = forward_hidden(params, tokens, cfg, mesh)
    # bf16 operands + f32 accumulation: full MXU rate, f32-exact softmax.
    logits = jnp.einsum("bsd,dv->bsv", x.astype(cfg.dtype),
                        _w_out(params, cfg).astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return constrain(logits, ("batch", "seq", "vocab"), mesh=mesh)


def fused_cross_entropy(x: jax.Array, w_out: jax.Array, targets: jax.Array,
                        cfg: TransformerConfig) -> jax.Array:
    """Chunked softmax cross-entropy that never materializes the full
    [B, S, V] logits (f32 logits for gpt2-small at B=32,S=1k are ~6 GB).

    Scans over token chunks; each step computes one [chunk, V] logits
    block, reduces it to per-token nll, and is rematerialized in the
    backward pass (jax.checkpoint), so peak memory is one block.
    """
    B, S, D = x.shape
    N = B * S
    chunk = min(cfg.xent_chunk or N, N)
    xf = x.reshape(N, D)
    tf = targets.reshape(N)
    n = -(-N // chunk)
    pad = n * chunk - N
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
        tf = jnp.pad(tf, (0, pad), constant_values=-1)
    wd = w_out.astype(cfg.dtype)

    def body(carry, inp):
        xc, tc = inp
        logits = jnp.einsum("cd,dv->cv", xc, wd,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, jnp.maximum(tc, 0)[:, None], axis=1)[:, 0]
        nll = jnp.where(tc >= 0, lse - tgt, 0.0)
        return carry + jnp.sum(nll), None

    total, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32),
        (xf.reshape(n, chunk, D), tf.reshape(n, chunk)))
    return total / N


def loss_fn(params, tokens, cfg: TransformerConfig, mesh=None
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy (+ MoE load-balance aux when MoE).
    tokens: [B, S]; predicts tokens[:,1:]."""
    targets = tokens[:, 1:]
    if cfg.xent_chunk is None:
        x, aux = forward_hidden_aux(params, tokens[:, :-1], cfg, mesh)
        logits = jnp.einsum("bsd,dv->bsv", x.astype(cfg.dtype),
                            _w_out(params, cfg).astype(cfg.dtype),
                            preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = jnp.mean(nll)
    else:
        x, aux = forward_hidden_aux(params, tokens[:, :-1], cfg, mesh)
        loss = fused_cross_entropy(x, _w_out(params, cfg), targets, cfg)
    metrics = {"loss": loss, "ppl": jnp.exp(loss)}
    if cfg.moe_experts > 0:
        metrics["moe_aux"] = aux
        loss = loss + cfg.moe_aux_weight * aux
        metrics["total_loss"] = loss
    return loss, metrics


def num_params(params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))
