"""KV-cache autoregressive decoding for serving.

The reference serves LLMs by delegating to vLLM on GPU (e.g.
doc/source/serve/doc_code/vllm_example.py); the TPU-native build owns
the decode loop itself, shaped for XLA:

* FIXED shapes everywhere: a slot-based cache [B, M, Hkv, Dh] per layer
  with B decode slots and M max positions — prefill and decode_step
  compile ONCE and are reused for the server's lifetime.
* decode_step advances every active slot one token per call (the inner
  loop of continuous batching): one [B,1,D] layer pass, scatter the new
  k/v into the caches with static-shape advanced indexing, attend
  against the full cache under a per-slot length mask.
* prefill runs the prompt through the stacked layers once (causal
  within the prompt), returning per-layer k/v to be inserted into a
  free slot.

Everything reuses transformer.py's parameter layout (init_params),
norms and RoPE, so any trained checkpoint serves unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import (TransformerConfig, _norm, _rope,
                                        _w_out)


class DecodeCaches(NamedTuple):
    """Per-layer KV caches + per-slot bookkeeping (all fixed-shape)."""

    k: jax.Array          # [L, B, M, Hkv, Dh]
    v: jax.Array          # [L, B, M, Hkv, Dh]
    lengths: jax.Array    # [B] int32 — tokens currently cached per slot
    last_token: jax.Array  # [B] int32 — input to the next decode step


def init_caches(cfg: TransformerConfig, num_slots: int,
                max_len: int) -> DecodeCaches:
    shape = (cfg.n_layers, num_slots, max_len, cfg.kv_heads,
             cfg.head_dim)
    return DecodeCaches(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        lengths=jnp.zeros((num_slots,), jnp.int32),
        last_token=jnp.zeros((num_slots,), jnp.int32))


def _qkv(p, h, cfg: TransformerConfig, positions):
    q = jnp.einsum("bsd,dhk->bshk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, p["wv"].astype(h.dtype))
    if cfg.arch == "llama":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _mlp(p, x, cfg: TransformerConfig):
    rms = cfg.arch == "llama"
    h = _norm(x, p["mlp_norm"], p.get("mlp_norm_b"), cfg.norm_eps, rms)
    if cfg.arch == "llama":
        gate = jnp.einsum("bsd,df->bsf", h, p["w_gate"].astype(h.dtype))
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"].astype(h.dtype))
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up
    else:
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"].astype(h.dtype))
        up = up + p["b_up"].astype(h.dtype)
        act = jax.nn.gelu(up.astype(jnp.float32)).astype(h.dtype)
    down = jnp.einsum("bsf,fd->bsd", act, p["w_down"].astype(act.dtype))
    if cfg.arch == "gpt2":
        down = down + p["b_down"].astype(down.dtype)
    return x + down


def _gqa_scores(q, k_cache, cfg: TransformerConfig):
    """q: [B,1,H,Dh], k_cache: [B,M,Hkv,Dh] -> scores [B,H,M] (f32)."""
    groups = cfg.n_heads // cfg.kv_heads
    B, M = k_cache.shape[0], k_cache.shape[1]
    qg = q[:, 0].reshape(B, cfg.kv_heads, groups, cfg.head_dim)
    s = jnp.einsum("bhgk,bmhk->bhgm", qg.astype(jnp.float32),
                   k_cache.astype(jnp.float32))
    return s.reshape(B, cfg.n_heads, M) / (cfg.head_dim ** 0.5)


def _decode_core(params: Dict[str, Any], caches: DecodeCaches,
                 active: jax.Array, cfg: TransformerConfig
                 ) -> Tuple[DecodeCaches, jax.Array]:
    """One decode step (traceable): greedy argmax and the last-token
    feedback happen ON DEVICE, so the host costs one small [B]-int
    transfer per read.  Safe to run extra steps on retired slots: every
    cache position is overwritten by its owner BEFORE it is first
    attended (scatter-at-pos precedes the mask reaching pos), so a
    reused slot never reads a predecessor's leftovers."""
    B = caches.lengths.shape[0]
    tokens = caches.last_token[:, None]                      # [B,1]
    pos = caches.lengths[:, None]                            # [B,1]
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [B,1,D]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][
            jnp.clip(pos, 0, cfg.max_seq - 1)].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    batch_ix = jnp.arange(B)
    M = caches.k.shape[2]
    # j attends iff j <= current position (cache holds pos new entries
    # after the scatter below, indices 0..pos inclusive of the new one).
    mask = jnp.arange(M)[None, :] <= pos                     # [B,M]

    def layer(x, inputs):
        p, k_cache, v_cache = inputs
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k_new, v_new = _qkv(p, h, cfg, pos)
        # Inactive slots must keep their cache untouched: a later,
        # shorter prompt reusing the slot would otherwise attend to the
        # garbage written at its old length position.
        gate = active[:, None, None]
        k_cache = k_cache.at[batch_ix, caches.lengths].set(
            jnp.where(gate, k_new[:, 0],
                      k_cache[batch_ix, caches.lengths]))
        v_cache = v_cache.at[batch_ix, caches.lengths].set(
            jnp.where(gate, v_new[:, 0],
                      v_cache[batch_ix, caches.lengths]))
        s = _gqa_scores(q, k_cache, cfg)                     # [B,H,M]
        s = jnp.where(mask[:, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        groups = cfg.n_heads // cfg.kv_heads
        wg = w.reshape(B, cfg.kv_heads, groups, M)
        o = jnp.einsum("bhgm,bmhk->bhgk", wg,
                       v_cache.astype(jnp.float32))
        o = o.reshape(B, 1, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o,
                          p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k_cache, v_cache)

    def scan_fn(x, inputs):
        x, kv = layer(x, inputs)
        return x, kv

    x, (k_all, v_all) = jax.lax.scan(
        scan_fn, x, (params["layers"], caches.k, caches.v))
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    logits = jnp.einsum(
        "bsd,dv->bsv", x.astype(jnp.float32),
        _w_out(params, cfg).astype(jnp.float32))[:, 0]       # [B,V]
    next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    new_last = jnp.where(active, next_tok, caches.last_token)
    new_len = jnp.where(active, caches.lengths + 1, caches.lengths)
    return DecodeCaches(k=k_all, v=v_all, lengths=new_len,
                        last_token=new_last), next_tok


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def decode_step(params: Dict[str, Any], caches: DecodeCaches,
                active: jax.Array, cfg: TransformerConfig
                ) -> Tuple[DecodeCaches, jax.Array]:
    """One token for every slot; returns (caches', next_tokens [B])."""
    return _decode_core(params, caches, active, cfg)


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps"),
                   donate_argnums=(1,))
def decode_steps(params: Dict[str, Any], caches: DecodeCaches,
                 active: jax.Array, cfg: TransformerConfig,
                 num_steps: int) -> Tuple[DecodeCaches, jax.Array]:
    """num_steps tokens per slot in ONE dispatch (lax.scan): returns
    (caches', tokens [num_steps, B]).  This is what makes serving fast
    through a high-latency host<->chip link: the per-read round trip
    (~60ms via a tunnel) amortizes over num_steps * B tokens instead of
    B."""

    def body(c, _):
        c, tok = _decode_core(params, c, active, cfg)
        return c, tok

    caches, toks = jax.lax.scan(body, caches, None, length=num_steps)
    return caches, toks


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill(params: Dict[str, Any], tokens: jax.Array, length: jax.Array,
            cfg: TransformerConfig
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prompt pass.  tokens: [1, P] int32 (padded), length: true length.
    Returns (k [L,P,Hkv,Dh], v [L,P,Hkv,Dh], last_logits [vocab])."""
    P = tokens.shape[1]
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [1,P,D]
    positions = jnp.arange(P, dtype=jnp.int32)[None]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][:P][None].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
    padmask = jnp.arange(P)[None, :] < length                # [1,P]

    def layer(x, p):
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k, v = _qkv(p, h, cfg, positions)
        groups = cfg.n_heads // cfg.kv_heads
        qg = q.reshape(1, P, cfg.kv_heads, groups, cfg.head_dim)
        s = jnp.einsum("bqhgk,bmhk->bhgqm", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) / (cfg.head_dim ** 0.5)
        s = jnp.where(causal[None, None, None], s, -jnp.inf)
        s = jnp.where(padmask[:, None, None, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgqm,bmhk->bqhgk", w, v.astype(jnp.float32))
        o = o.reshape(1, P, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k[0], v[0])

    x, (k_all, v_all) = jax.lax.scan(layer, x, params["layers"])
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    last = x[0, jnp.clip(length - 1, 0, P - 1)]
    logits = last.astype(jnp.float32) @ _w_out(params, cfg).astype(
        jnp.float32)
    return k_all, v_all, logits


def _prefill_insert_core(params: Dict[str, Any], caches: DecodeCaches,
                         tokens: jax.Array, lengths: jax.Array,
                         slots: jax.Array, valid: jax.Array,
                         cfg: TransformerConfig
                         ) -> Tuple[DecodeCaches, jax.Array]:
    """Traceable body shared by prefill_insert and the fused
    admission+decode step."""
    N, P = tokens.shape
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [N,P,D]
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (N, P))
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][:P][None].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
    padmask = jnp.arange(P)[None, :] < lengths[:, None]      # [N,P]

    def layer(x, p):
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k, v = _qkv(p, h, cfg, positions)
        groups = cfg.n_heads // cfg.kv_heads
        qg = q.reshape(N, P, cfg.kv_heads, groups, cfg.head_dim)
        s = jnp.einsum("bqhgk,bmhk->bhgqm", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) / (cfg.head_dim ** 0.5)
        s = jnp.where(causal[None, None, None], s, -jnp.inf)
        s = jnp.where(padmask[:, None, None, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgqm,bmhk->bqhgk", w, v.astype(jnp.float32))
        o = o.reshape(N, P, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer, x, params["layers"])
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    last_ix = jnp.clip(lengths - 1, 0, P - 1)
    last = x[jnp.arange(N), last_ix]                         # [N,D]
    logits = last.astype(jnp.float32) @ _w_out(params, cfg).astype(
        jnp.float32)
    first_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scatter into the cache: [L,N,P,...] -> positions [:, slot, :P].
    gate = valid[None, :, None, None, None]
    old_k = caches.k[:, slots, :P]
    old_v = caches.v[:, slots, :P]
    ck = caches.k.at[:, slots, :P].set(
        jnp.where(gate, k_all.astype(caches.k.dtype), old_k))
    cv = caches.v.at[:, slots, :P].set(
        jnp.where(gate, v_all.astype(caches.v.dtype), old_v))
    new_len = caches.lengths.at[slots].set(
        jnp.where(valid, lengths, caches.lengths[slots]))
    new_last = caches.last_token.at[slots].set(
        jnp.where(valid, first_tok, caches.last_token[slots]))
    return DecodeCaches(k=ck, v=cv, lengths=new_len,
                        last_token=new_last), first_tok


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnums=(1,))
def prefill_insert(params: Dict[str, Any], caches: DecodeCaches,
                   tokens: jax.Array, lengths: jax.Array,
                   slots: jax.Array, valid: jax.Array,
                   cfg: TransformerConfig
                   ) -> Tuple[DecodeCaches, jax.Array]:
    """Batched prefill of up to N prompts + cache insertion in ONE
    dispatch.  tokens: [N, P] int32 (padded), lengths/slots/valid: [N].
    Invalid rows rewrite their target slot with its existing contents
    (gather-then-scatter no-op).  Returns (caches', first_tokens [N]).

    Serving admission is the other latency cliff besides decode reads:
    one serial prefill+sync per request costs ~70ms each through a
    tunnel; batching them makes 16 admissions cost the same as one."""
    return _prefill_insert_core(params, caches, tokens, lengths, slots,
                                valid, cfg)


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps",
                                             "prompt_pad"),
                   donate_argnums=(1,))
def prefill_decode_packed(params: Dict[str, Any], caches: DecodeCaches,
                          packed: jax.Array, cfg: TransformerConfig,
                          num_steps: int, prompt_pad: int
                          ) -> Tuple[DecodeCaches, jax.Array,
                                     jax.Array]:
    """prefill_decode_fused with ALL host-side inputs in ONE int32
    array — through a tunneled chip every separate host->device
    transfer pays link latency, so the engine packs
    tokens/lengths/slots/valid/active into a single upload.

    packed: [N+1, W] int32 with W = max(prompt_pad + 3, num_slots);
      rows 0..N-1: [tokens[0:P] | length | slot | valid]
      row  N:      active mask for the B decode slots in cols 0..B-1.
    """
    P = prompt_pad
    B = caches.lengths.shape[0]
    tokens = packed[:-1, :P]
    lengths = packed[:-1, P]
    slots = packed[:-1, P + 1]
    valid = packed[:-1, P + 2] > 0
    active = packed[-1, :B] > 0
    caches, first = _prefill_insert_core(params, caches, tokens,
                                         lengths, slots, valid, cfg)
    active = active.at[slots].set(jnp.where(valid, True, active[slots]))

    def body(c, _):
        return _decode_core(params, c, active, cfg)

    caches, toks = jax.lax.scan(body, caches, None, length=num_steps)
    return caches, first, toks


@functools.partial(jax.jit, donate_argnums=(0,))
def insert_slot(caches: DecodeCaches, slot: jax.Array, k: jax.Array,
                v: jax.Array, length: jax.Array, first_token: jax.Array
                ) -> DecodeCaches:
    """Install a prefilled request into a decode slot.  k/v: [L,P,...];
    P <= M (cache width) — padded positions beyond `length` are masked
    by the per-slot length at attention time."""
    P = k.shape[1]
    ck = caches.k.at[:, slot, :P].set(k.astype(caches.k.dtype))
    cv = caches.v.at[:, slot, :P].set(v.astype(caches.v.dtype))
    return DecodeCaches(
        k=ck, v=cv,
        lengths=caches.lengths.at[slot].set(length),
        last_token=caches.last_token.at[slot].set(first_token))


def set_last_tokens(caches: DecodeCaches,
                    tokens: jax.Array) -> DecodeCaches:
    return caches._replace(last_token=tokens)
