"""KV-cache autoregressive decoding for serving.

The reference serves LLMs by delegating to vLLM on GPU (e.g.
doc/source/serve/doc_code/vllm_example.py); the TPU-native build owns
the decode loop itself, shaped for XLA:

* FIXED shapes everywhere — prefill and decode_step compile ONCE and
  are reused for the server's lifetime.  Two cache layouts share that
  property: the dense per-slot cache [B, M, Hkv, Dh] (DecodeCaches,
  every slot reserves M max positions) and the PAGED cache
  (PagedDecodeCaches below: a [NB, bs, Hkv, Dh] block pool addressed
  through per-slot block tables, so memory scales with tokens actually
  cached and full blocks are shareable across requests).
* decode_step advances every active slot one token per call (the inner
  loop of continuous batching): one [B,1,D] layer pass, scatter the new
  k/v into the caches with static-shape advanced indexing, attend
  against the full cache under a per-slot length mask (paged variants
  scatter/gather through the block table instead).
* prefill runs the prompt through the stacked layers once (causal
  within the prompt), returning per-layer k/v to be inserted into a
  free slot; the paged analog prefills only the prompt's uncached
  SUFFIX against a gathered cached-prefix window.

Everything reuses transformer.py's parameter layout (init_params),
norms and RoPE, so any trained checkpoint serves unchanged.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import (TransformerConfig, _norm, _rope,
                                        _w_out)


class DecodeCaches(NamedTuple):
    """Per-layer KV caches + per-slot bookkeeping (all fixed-shape)."""

    k: jax.Array          # [L, B, M, Hkv, Dh]
    v: jax.Array          # [L, B, M, Hkv, Dh]
    lengths: jax.Array    # [B] int32 — tokens currently cached per slot
    last_token: jax.Array  # [B] int32 — input to the next decode step


def init_caches(cfg: TransformerConfig, num_slots: int,
                max_len: int) -> DecodeCaches:
    shape = (cfg.n_layers, num_slots, max_len, cfg.kv_heads,
             cfg.head_dim)
    return DecodeCaches(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        lengths=jnp.zeros((num_slots,), jnp.int32),
        last_token=jnp.zeros((num_slots,), jnp.int32))


def _qkv(p, h, cfg: TransformerConfig, positions):
    q = jnp.einsum("bsd,dhk->bshk", h, p["wq"].astype(h.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, p["wk"].astype(h.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, p["wv"].astype(h.dtype))
    if cfg.arch == "llama":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _mlp(p, x, cfg: TransformerConfig):
    rms = cfg.arch == "llama"
    h = _norm(x, p["mlp_norm"], p.get("mlp_norm_b"), cfg.norm_eps, rms)
    if cfg.arch == "llama":
        gate = jnp.einsum("bsd,df->bsf", h, p["w_gate"].astype(h.dtype))
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"].astype(h.dtype))
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(h.dtype) * up
    else:
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"].astype(h.dtype))
        up = up + p["b_up"].astype(h.dtype)
        act = jax.nn.gelu(up.astype(jnp.float32)).astype(h.dtype)
    down = jnp.einsum("bsf,fd->bsd", act, p["w_down"].astype(act.dtype))
    if cfg.arch == "gpt2":
        down = down + p["b_down"].astype(down.dtype)
    return x + down


def _gqa_scores(q, k_cache, cfg: TransformerConfig):
    """q: [B,1,H,Dh], k_cache: [B,M,Hkv,Dh] -> scores [B,H,M] (f32)."""
    groups = cfg.n_heads // cfg.kv_heads
    B, M = k_cache.shape[0], k_cache.shape[1]
    qg = q[:, 0].reshape(B, cfg.kv_heads, groups, cfg.head_dim)
    s = jnp.einsum("bhgk,bmhk->bhgm", qg.astype(jnp.float32),
                   k_cache.astype(jnp.float32))
    return s.reshape(B, cfg.n_heads, M) / (cfg.head_dim ** 0.5)


def _decode_core(params: Dict[str, Any], caches: DecodeCaches,
                 active: jax.Array, cfg: TransformerConfig
                 ) -> Tuple[DecodeCaches, jax.Array]:
    """One decode step (traceable): greedy argmax and the last-token
    feedback happen ON DEVICE, so the host costs one small [B]-int
    transfer per read.  Safe to run extra steps on retired slots: every
    cache position is overwritten by its owner BEFORE it is first
    attended (scatter-at-pos precedes the mask reaching pos), so a
    reused slot never reads a predecessor's leftovers."""
    B = caches.lengths.shape[0]
    tokens = caches.last_token[:, None]                      # [B,1]
    pos = caches.lengths[:, None]                            # [B,1]
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [B,1,D]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][
            jnp.clip(pos, 0, cfg.max_seq - 1)].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    batch_ix = jnp.arange(B)
    M = caches.k.shape[2]
    # j attends iff j <= current position (cache holds pos new entries
    # after the scatter below, indices 0..pos inclusive of the new one).
    mask = jnp.arange(M)[None, :] <= pos                     # [B,M]

    def layer(x, inputs):
        p, k_cache, v_cache = inputs
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k_new, v_new = _qkv(p, h, cfg, pos)
        # Inactive slots must keep their cache untouched: a later,
        # shorter prompt reusing the slot would otherwise attend to the
        # garbage written at its old length position.
        gate = active[:, None, None]
        k_cache = k_cache.at[batch_ix, caches.lengths].set(
            jnp.where(gate, k_new[:, 0],
                      k_cache[batch_ix, caches.lengths]))
        v_cache = v_cache.at[batch_ix, caches.lengths].set(
            jnp.where(gate, v_new[:, 0],
                      v_cache[batch_ix, caches.lengths]))
        s = _gqa_scores(q, k_cache, cfg)                     # [B,H,M]
        s = jnp.where(mask[:, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        groups = cfg.n_heads // cfg.kv_heads
        wg = w.reshape(B, cfg.kv_heads, groups, M)
        o = jnp.einsum("bhgm,bmhk->bhgk", wg,
                       v_cache.astype(jnp.float32))
        o = o.reshape(B, 1, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o,
                          p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k_cache, v_cache)

    def scan_fn(x, inputs):
        x, kv = layer(x, inputs)
        return x, kv

    x, (k_all, v_all) = jax.lax.scan(
        scan_fn, x, (params["layers"], caches.k, caches.v))
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    logits = jnp.einsum(
        "bsd,dv->bsv", x.astype(jnp.float32),
        _w_out(params, cfg).astype(jnp.float32))[:, 0]       # [B,V]
    next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    new_last = jnp.where(active, next_tok, caches.last_token)
    new_len = jnp.where(active, caches.lengths + 1, caches.lengths)
    return DecodeCaches(k=k_all, v=v_all, lengths=new_len,
                        last_token=new_last), next_tok


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def decode_step(params: Dict[str, Any], caches: DecodeCaches,
                active: jax.Array, cfg: TransformerConfig
                ) -> Tuple[DecodeCaches, jax.Array]:
    """One token for every slot; returns (caches', next_tokens [B])."""
    return _decode_core(params, caches, active, cfg)


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps"),
                   donate_argnums=(1,))
def decode_steps(params: Dict[str, Any], caches: DecodeCaches,
                 active: jax.Array, cfg: TransformerConfig,
                 num_steps: int) -> Tuple[DecodeCaches, jax.Array]:
    """num_steps tokens per slot in ONE dispatch (lax.scan): returns
    (caches', tokens [num_steps, B]).  This is what makes serving fast
    through a high-latency host<->chip link: the per-read round trip
    (~60ms via a tunnel) amortizes over num_steps * B tokens instead of
    B."""

    def body(c, _):
        c, tok = _decode_core(params, c, active, cfg)
        return c, tok

    caches, toks = jax.lax.scan(body, caches, None, length=num_steps)
    return caches, toks


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill(params: Dict[str, Any], tokens: jax.Array, length: jax.Array,
            cfg: TransformerConfig
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prompt pass.  tokens: [1, P] int32 (padded), length: true length.
    Returns (k [L,P,Hkv,Dh], v [L,P,Hkv,Dh], last_logits [vocab])."""
    P = tokens.shape[1]
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [1,P,D]
    positions = jnp.arange(P, dtype=jnp.int32)[None]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][:P][None].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
    padmask = jnp.arange(P)[None, :] < length                # [1,P]

    def layer(x, p):
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k, v = _qkv(p, h, cfg, positions)
        groups = cfg.n_heads // cfg.kv_heads
        qg = q.reshape(1, P, cfg.kv_heads, groups, cfg.head_dim)
        s = jnp.einsum("bqhgk,bmhk->bhgqm", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) / (cfg.head_dim ** 0.5)
        s = jnp.where(causal[None, None, None], s, -jnp.inf)
        s = jnp.where(padmask[:, None, None, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgqm,bmhk->bqhgk", w, v.astype(jnp.float32))
        o = o.reshape(1, P, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k[0], v[0])

    x, (k_all, v_all) = jax.lax.scan(layer, x, params["layers"])
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    last = x[0, jnp.clip(length - 1, 0, P - 1)]
    logits = last.astype(jnp.float32) @ _w_out(params, cfg).astype(
        jnp.float32)
    return k_all, v_all, logits


def _prefill_insert_core(params: Dict[str, Any], caches: DecodeCaches,
                         tokens: jax.Array, lengths: jax.Array,
                         slots: jax.Array, valid: jax.Array,
                         cfg: TransformerConfig
                         ) -> Tuple[DecodeCaches, jax.Array]:
    """Traceable body shared by prefill_insert and the fused
    admission+decode step."""
    N, P = tokens.shape
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [N,P,D]
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (N, P))
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][:P][None].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
    padmask = jnp.arange(P)[None, :] < lengths[:, None]      # [N,P]

    def layer(x, p):
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k, v = _qkv(p, h, cfg, positions)
        groups = cfg.n_heads // cfg.kv_heads
        qg = q.reshape(N, P, cfg.kv_heads, groups, cfg.head_dim)
        s = jnp.einsum("bqhgk,bmhk->bhgqm", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) / (cfg.head_dim ** 0.5)
        s = jnp.where(causal[None, None, None], s, -jnp.inf)
        s = jnp.where(padmask[:, None, None, None, :], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgqm,bmhk->bqhgk", w, v.astype(jnp.float32))
        o = o.reshape(N, P, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer, x, params["layers"])
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    last_ix = jnp.clip(lengths - 1, 0, P - 1)
    last = x[jnp.arange(N), last_ix]                         # [N,D]
    logits = last.astype(jnp.float32) @ _w_out(params, cfg).astype(
        jnp.float32)
    first_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scatter into the cache: [L,N,P,...] -> positions [:, slot, :P].
    gate = valid[None, :, None, None, None]
    old_k = caches.k[:, slots, :P]
    old_v = caches.v[:, slots, :P]
    ck = caches.k.at[:, slots, :P].set(
        jnp.where(gate, k_all.astype(caches.k.dtype), old_k))
    cv = caches.v.at[:, slots, :P].set(
        jnp.where(gate, v_all.astype(caches.v.dtype), old_v))
    new_len = caches.lengths.at[slots].set(
        jnp.where(valid, lengths, caches.lengths[slots]))
    new_last = caches.last_token.at[slots].set(
        jnp.where(valid, first_tok, caches.last_token[slots]))
    return DecodeCaches(k=ck, v=cv, lengths=new_len,
                        last_token=new_last), first_tok


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnums=(1,))
def prefill_insert(params: Dict[str, Any], caches: DecodeCaches,
                   tokens: jax.Array, lengths: jax.Array,
                   slots: jax.Array, valid: jax.Array,
                   cfg: TransformerConfig
                   ) -> Tuple[DecodeCaches, jax.Array]:
    """Batched prefill of up to N prompts + cache insertion in ONE
    dispatch.  tokens: [N, P] int32 (padded), lengths/slots/valid: [N].
    Invalid rows rewrite their target slot with its existing contents
    (gather-then-scatter no-op).  Returns (caches', first_tokens [N]).

    Serving admission is the other latency cliff besides decode reads:
    one serial prefill+sync per request costs ~70ms each through a
    tunnel; batching them makes 16 admissions cost the same as one."""
    return _prefill_insert_core(params, caches, tokens, lengths, slots,
                                valid, cfg)


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps",
                                             "prompt_pad"),
                   donate_argnums=(1,))
def prefill_decode_packed(params: Dict[str, Any], caches: DecodeCaches,
                          packed: jax.Array, cfg: TransformerConfig,
                          num_steps: int, prompt_pad: int
                          ) -> Tuple[DecodeCaches, jax.Array,
                                     jax.Array]:
    """prefill_decode_fused with ALL host-side inputs in ONE int32
    array — through a tunneled chip every separate host->device
    transfer pays link latency, so the engine packs
    tokens/lengths/slots/valid/active into a single upload.

    packed: [N+1, W] int32 with W = max(prompt_pad + 3, num_slots);
      rows 0..N-1: [tokens[0:P] | length | slot | valid]
      row  N:      active mask for the B decode slots in cols 0..B-1.
    """
    P = prompt_pad
    B = caches.lengths.shape[0]
    tokens = packed[:-1, :P]
    lengths = packed[:-1, P]
    slots = packed[:-1, P + 1]
    valid = packed[:-1, P + 2] > 0
    active = packed[-1, :B] > 0
    caches, first = _prefill_insert_core(params, caches, tokens,
                                         lengths, slots, valid, cfg)
    active = active.at[slots].set(jnp.where(valid, True, active[slots]))

    def body(c, _):
        return _decode_core(params, c, active, cfg)

    caches, toks = jax.lax.scan(body, caches, None, length=num_steps)
    return caches, first, toks


@functools.partial(jax.jit, donate_argnums=(0,))
def insert_slot(caches: DecodeCaches, slot: jax.Array, k: jax.Array,
                v: jax.Array, length: jax.Array, first_token: jax.Array
                ) -> DecodeCaches:
    """Install a prefilled request into a decode slot.  k/v: [L,P,...];
    P <= M (cache width) — padded positions beyond `length` are masked
    by the per-slot length at attention time."""
    P = k.shape[1]
    ck = caches.k.at[:, slot, :P].set(k.astype(caches.k.dtype))
    cv = caches.v.at[:, slot, :P].set(v.astype(caches.v.dtype))
    return DecodeCaches(
        k=ck, v=cv,
        lengths=caches.lengths.at[slot].set(length),
        last_token=caches.last_token.at[slot].set(first_token))


def set_last_tokens(caches: DecodeCaches,
                    tokens: jax.Array) -> DecodeCaches:
    return caches._replace(last_token=tokens)


# ===========================================================================
# Paged KV cache (block pool + per-slot block tables)
# ===========================================================================
# The dense DecodeCaches above reserves max_len positions per slot; the
# paged variant stores KV in fixed-size blocks from a shared pool and
# addresses them through per-slot block tables, so short sequences use
# blocks proportional to their length and FULL prompt blocks are
# refcount-shareable across requests (the serve/llm.py prefix cache).
# Decode attention goes through ops/paged_attention.py (Pallas ragged
# paged attention on TPU, jnp.take gather reference elsewhere).
#
# Invariants the engine (serve/llm.py) maintains, which these kernels
# rely on:
#   * pool block 0 is a reserved scratch block: never allocated, table
#     padding points at it, and gated/over-capacity writes are
#     redirected to it — so duplicate scatter targets always carry the
#     same value and garbage positions are always masked by length;
#   * a request's prefix_len is a multiple of the block size (only
#     FULL blocks are shared), so every suffix/decode write lands in a
#     block owned exclusively by that slot;
#   * admission pre-allocates blocks for prompt + max_new tokens, so
#     decode never needs to allocate (and never runs out mid-decode).


class PagedDecodeCaches(NamedTuple):
    """Block-pool KV + per-slot tables (all fixed-shape)."""

    kp: jax.Array            # [L, NB, bs, Hkv, Dh] block pool
    vp: jax.Array            # [L, NB, bs, Hkv, Dh]
    block_tables: jax.Array  # [B, W] int32 — physical block per logical
    lengths: jax.Array       # [B] int32 — tokens currently cached
    last_token: jax.Array    # [B] int32 — input to the next decode step


def paged_table_width(max_len: int, block_size: int) -> int:
    """Logical blocks per slot (ceil)."""
    return -(-max_len // block_size)


def init_paged_caches(cfg: TransformerConfig, num_slots: int,
                      num_blocks: int, block_size: int,
                      max_len: int) -> PagedDecodeCaches:
    """`num_blocks` USABLE blocks; one extra scratch block (id 0) is
    added internally, so pool ids run 0..num_blocks inclusive."""
    w = paged_table_width(max_len, block_size)
    shape = (cfg.n_layers, num_blocks + 1, block_size, cfg.kv_heads,
             cfg.head_dim)
    return PagedDecodeCaches(
        kp=jnp.zeros(shape, cfg.dtype),
        vp=jnp.zeros(shape, cfg.dtype),
        block_tables=jnp.zeros((num_slots, w), jnp.int32),
        lengths=jnp.zeros((num_slots,), jnp.int32),
        last_token=jnp.zeros((num_slots,), jnp.int32))


def _paged_decode_core(params: Dict[str, Any], caches: PagedDecodeCaches,
                       active: jax.Array, cfg: TransformerConfig,
                       attn_impl: str = "auto"
                       ) -> Tuple[PagedDecodeCaches, jax.Array]:
    """One decode step over the block pool (traceable).  Mirrors
    _decode_core exactly, with the scatter routed through the block
    table and attention through ops.paged_attention.  Safe to run extra
    steps on retired/drained slots: their writes are clamped into their
    own private tail blocks or redirected to scratch block 0, and their
    garbage outputs are dropped host-side."""
    from ray_tpu.ops import paged_attention as _pa

    B = caches.lengths.shape[0]
    bs = caches.kp.shape[2]
    M = caches.block_tables.shape[1] * bs
    tokens = caches.last_token[:, None]                      # [B,1]
    pos = caches.lengths[:, None]                            # [B,1]
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [B,1,D]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][
            jnp.clip(pos, 0, cfg.max_seq - 1)].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    batch_ix = jnp.arange(B)
    # Clamp the write position for slots decoding past their
    # allocation (drained slots kept hot by the dispatcher); the
    # active gate below redirects inactive slots to scratch block 0.
    pos_c = jnp.minimum(caches.lengths, M - 1)
    blk_w = jnp.where(active,
                      caches.block_tables[batch_ix, pos_c // bs], 0)
    off_w = pos_c % bs
    # Valid positions INCLUDE the token scattered this step.
    ctx_lens = jnp.minimum(caches.lengths + 1, M)

    def layer(x, inputs):
        p, k_pool, v_pool = inputs
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k_new, v_new = _qkv(p, h, cfg, pos)
        gate = active[:, None, None]
        k_pool = k_pool.at[blk_w, off_w].set(
            jnp.where(gate, k_new[:, 0].astype(k_pool.dtype),
                      k_pool[blk_w, off_w]))
        v_pool = v_pool.at[blk_w, off_w].set(
            jnp.where(gate, v_new[:, 0].astype(v_pool.dtype),
                      v_pool[blk_w, off_w]))
        o = _pa.paged_attention(q[:, 0], k_pool, v_pool,
                                caches.block_tables, ctx_lens,
                                impl=attn_impl)              # [B,H,Dh]
        attn = jnp.einsum("bshk,hkd->bsd", o[:, None].astype(cfg.dtype),
                          p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k_pool, v_pool)

    x, (kp_all, vp_all) = jax.lax.scan(
        layer, x, (params["layers"], caches.kp, caches.vp))
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    logits = jnp.einsum(
        "bsd,dv->bsv", x.astype(jnp.float32),
        _w_out(params, cfg).astype(jnp.float32))[:, 0]       # [B,V]
    next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    new_last = jnp.where(active, next_tok, caches.last_token)
    new_len = jnp.where(active, caches.lengths + 1, caches.lengths)
    return PagedDecodeCaches(kp=kp_all, vp=vp_all,
                             block_tables=caches.block_tables,
                             lengths=new_len,
                             last_token=new_last), next_tok


@functools.partial(jax.jit, static_argnames=("cfg", "attn_impl"),
                   donate_argnums=(1,))
def paged_decode_step(params: Dict[str, Any], caches: PagedDecodeCaches,
                      active: jax.Array, cfg: TransformerConfig,
                      attn_impl: str = "auto"
                      ) -> Tuple[PagedDecodeCaches, jax.Array]:
    """One token for every slot; returns (caches', next_tokens [B])."""
    return _paged_decode_core(params, caches, active, cfg, attn_impl)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "num_steps", "attn_impl"),
                   donate_argnums=(1,))
def paged_decode_steps(params: Dict[str, Any], caches: PagedDecodeCaches,
                       active: jax.Array, cfg: TransformerConfig,
                       num_steps: int, attn_impl: str = "auto"
                       ) -> Tuple[PagedDecodeCaches, jax.Array]:
    """num_steps tokens per slot in ONE dispatch (lax.scan): returns
    (caches', tokens [num_steps, B])."""

    def body(c, _):
        return _paged_decode_core(params, c, active, cfg, attn_impl)

    caches, toks = jax.lax.scan(body, caches, None, length=num_steps)
    return caches, toks


def _paged_prefill_core(params: Dict[str, Any],
                        caches: PagedDecodeCaches, tokens: jax.Array,
                        suffix_lens: jax.Array, prefix_lens: jax.Array,
                        slots: jax.Array, valid: jax.Array,
                        new_bt: jax.Array, cfg: TransformerConfig
                        ) -> Tuple[PagedDecodeCaches, jax.Array]:
    """Suffix prefill against a paged prefix (traceable).

    tokens [N, P] hold only each prompt's UNCACHED suffix; the cached
    prefix (prefix_lens tokens, whole blocks, already resident in the
    pool via the request's block table) is attended by gather, never
    recomputed — this is where a prefix-cache hit saves its FLOPs.
    Suffix queries sit at absolute positions prefix_len + i (RoPE /
    learned positions stay correct), attend all prefix positions plus
    causally within the suffix, and their K/V are scattered into the
    slot's private blocks.  prefix_lens == 0 degenerates to the dense
    prefill math.  Invalid rows rewrite existing state (gather-then-
    scatter no-op), exactly like _prefill_insert_core."""
    N, P = tokens.shape
    bs = caches.kp.shape[2]
    W = caches.block_tables.shape[1]
    M = W * bs
    bt = caches.block_tables.at[slots].set(
        jnp.where(valid[:, None], new_bt, caches.block_tables[slots]))
    bt_rows = bt[slots]                                      # [N, W]
    positions = prefix_lens[:, None] + jnp.arange(P, dtype=jnp.int32)
    x = params["tok_embed"][tokens].astype(cfg.dtype)        # [N,P,D]
    if cfg.arch == "gpt2":
        x = x + params["pos_embed"][
            jnp.clip(positions, 0, cfg.max_seq - 1)].astype(cfg.dtype)
    rms = cfg.arch == "llama"
    causal = (jnp.arange(P)[:, None] >= jnp.arange(P)[None, :])
    padmask = jnp.arange(P)[None, :] < suffix_lens[:, None]  # [N,P]
    ctx_mask = jnp.arange(M)[None, :] < prefix_lens[:, None]  # [N,M]
    # keys layout: [0..M) gathered pool window, [M..M+P) in-flight
    # suffix — full mask [N, P, M+P].
    mask_full = jnp.concatenate([
        jnp.broadcast_to(ctx_mask[:, None, :], (N, P, M)),
        causal[None] & padmask[:, None, :],
    ], axis=-1)
    # Scatter targets for the suffix K/V (clamped + gated to scratch).
    abs_pos = jnp.minimum(positions, M - 1)                  # [N,P]
    blkidx = jnp.take_along_axis(bt_rows, abs_pos // bs, axis=1)
    offidx = abs_pos % bs
    wgate = valid[:, None] & padmask                         # [N,P]
    blk_w = jnp.where(wgate, blkidx, 0)
    groups = cfg.n_heads // cfg.kv_heads

    def layer(x, inputs):
        p, k_pool, v_pool = inputs
        h = _norm(x, p["attn_norm"], p.get("attn_norm_b"),
                  cfg.norm_eps, rms)
        q, k, v = _qkv(p, h, cfg, positions)
        k_pool = k_pool.at[blk_w, offidx].set(
            jnp.where(wgate[..., None, None], k.astype(k_pool.dtype),
                      k_pool[blk_w, offidx]))
        v_pool = v_pool.at[blk_w, offidx].set(
            jnp.where(wgate[..., None, None], v.astype(v_pool.dtype),
                      v_pool[blk_w, offidx]))
        # Prefix window gather (suffix positions in it are masked off).
        k_ctx = jnp.take(k_pool, bt_rows, axis=0).reshape(
            N, M, cfg.kv_heads, cfg.head_dim)
        v_ctx = jnp.take(v_pool, bt_rows, axis=0).reshape(
            N, M, cfg.kv_heads, cfg.head_dim)
        k_all = jnp.concatenate([k_ctx.astype(k.dtype), k], axis=1)
        v_all = jnp.concatenate([v_ctx.astype(v.dtype), v], axis=1)
        qg = q.reshape(N, P, cfg.kv_heads, groups, cfg.head_dim)
        s = jnp.einsum("bqhgk,bmhk->bhgqm", qg.astype(jnp.float32),
                       k_all.astype(jnp.float32)) / (cfg.head_dim ** 0.5)
        s = jnp.where(mask_full[:, None, None], s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhgqm,bmhk->bqhgk", w, v_all.astype(jnp.float32))
        o = o.reshape(N, P, cfg.n_heads, cfg.head_dim).astype(cfg.dtype)
        attn = jnp.einsum("bshk,hkd->bsd", o, p["wo"].astype(cfg.dtype))
        x = x + attn
        x = _mlp(p, x, cfg)
        return x, (k_pool, v_pool)

    x, (kp_all, vp_all) = jax.lax.scan(
        layer, x, (params["layers"], caches.kp, caches.vp))
    x = _norm(x, params["final_norm"], params.get("final_norm_b"),
              cfg.norm_eps, rms)
    last_ix = jnp.clip(suffix_lens - 1, 0, P - 1)
    last = x[jnp.arange(N), last_ix]                         # [N,D]
    logits = last.astype(jnp.float32) @ _w_out(params, cfg).astype(
        jnp.float32)
    first_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    total = prefix_lens + suffix_lens
    new_len = caches.lengths.at[slots].set(
        jnp.where(valid, total, caches.lengths[slots]))
    new_last = caches.last_token.at[slots].set(
        jnp.where(valid, first_tok, caches.last_token[slots]))
    return PagedDecodeCaches(kp=kp_all, vp=vp_all, block_tables=bt,
                             lengths=new_len,
                             last_token=new_last), first_tok


@functools.partial(jax.jit, static_argnames=("cfg", "num_steps",
                                             "prompt_pad", "attn_impl"),
                   donate_argnums=(1,))
def paged_prefill_decode_packed(params: Dict[str, Any],
                                caches: PagedDecodeCaches,
                                packed: jax.Array,
                                cfg: TransformerConfig, num_steps: int,
                                prompt_pad: int, attn_impl: str = "auto"
                                ) -> Tuple[PagedDecodeCaches, jax.Array,
                                           jax.Array]:
    """Fused suffix-prefill + chunked decode with ALL host inputs in
    ONE int32 upload (the paged analog of prefill_decode_packed).

    packed: [N+1, Wp] int32 with W = table width and
    Wp = max(prompt_pad + 4 + W, num_slots);
      rows 0..N-1: [suffix_tokens[0:P] | suffix_len | prefix_len |
                    slot | valid | block_table[0:W]]
      row  N:      active mask for the B decode slots in cols 0..B-1.
    """
    P = prompt_pad
    B = caches.lengths.shape[0]
    W = caches.block_tables.shape[1]
    tokens = packed[:-1, :P]
    suffix_lens = packed[:-1, P]
    prefix_lens = packed[:-1, P + 1]
    slots = packed[:-1, P + 2]
    valid = packed[:-1, P + 3] > 0
    new_bt = packed[:-1, P + 4:P + 4 + W]
    active = packed[-1, :B] > 0
    caches, first = _paged_prefill_core(params, caches, tokens,
                                        suffix_lens, prefix_lens, slots,
                                        valid, new_bt, cfg)
    active = active.at[slots].set(jnp.where(valid, True, active[slots]))

    def body(c, _):
        return _paged_decode_core(params, c, active, cfg, attn_impl)

    caches, toks = jax.lax.scan(body, caches, None, length=num_steps)
    return caches, first, toks
