// Shared-memory object store — TPU-native analog of the reference's plasma
// store (reference: src/ray/object_manager/plasma/store.h:55,
// object_lifecycle_manager.h:101, eviction_policy.h:160, dlmalloc.cc).
//
// Design differences from the reference, on purpose:
//  * The store is a single mmap'ed file (tmpfs/shm) shared by every process
//    on the host; there is no store *server* process brokering access over a
//    unix socket + fd-passing (plasma.fbs / fling.cc).  Instead the object
//    table, allocator and eviction policy live *inside* the shared segment,
//    guarded by a robust process-shared mutex, and every worker links this
//    library and operates on the segment directly.  That removes a
//    round-trip from the create/get hot path entirely (the reference pays a
//    UDS RPC per create/get) while keeping crash-safety via robust futexes.
//  * Allocation is a first-fit free list with boundary-tag coalescing over
//    the data region (the reference uses dlmalloc-on-mmap).
//  * Eviction is LRU over sealed, refcount-zero objects, exactly like the
//    reference's LRUCache (eviction_policy.h:105).
//
// Exported as a plain C ABI for ctypes.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <utility>
#include <vector>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <signal.h>

namespace {

constexpr uint64_t kMagic = 0x5254505553544f32ULL;  // "RTPUSTO2"
constexpr uint32_t kIdSize = 16;
constexpr uint64_t kAlign = 64;
constexpr uint32_t kTableCapacity = 1 << 16;  // 65536 entries, power of two
constexpr uint32_t kMaxClients = 64;         // concurrent pinning processes
// Distinct concurrently-pinned objects tracked per client; beyond this,
// pins still work (refcnt) but are untracked by the reaper.
constexpr uint32_t kClientPinCap = 1 << 9;

// ---- status codes (keep in sync with _private/shm_store.py) ----
constexpr int kOK = 0;
constexpr int kNotFound = -1;
constexpr int kExists = -2;
constexpr int kFull = -3;
constexpr int kCreating = -4;
constexpr int kError = -5;
constexpr int kTableFull = -6;
constexpr int kNoPin = -7;  // transfer: from_pid has no recorded pin

enum ObjState : uint32_t {
  kEmpty = 0,
  kStateCreating = 1,
  kSealed = 2,
  kTombstone = 3,  // deleted slot, reusable on insert, skipped on probe-stop
};

struct Entry {
  uint8_t id[kIdSize];
  uint64_t offset;      // file offset of object payload
  uint64_t size;        // payload bytes
  uint64_t lru_tick;    // last-touched tick for LRU eviction
  uint32_t state;
  uint32_t refcnt;
  uint32_t pending_delete;
  uint32_t creator_pid;  // pid that called create_object (stale-reset gate)
};

// Allocator block header (boundary tags). Lives immediately before each
// payload in the data region. Sizes include the header itself.
struct Block {
  uint64_t size;       // total block size incl. header
  uint64_t prev_size;  // size of the physically previous block (0 if first)
  uint32_t free;
  uint32_t pad;
  // When free, the first 16 payload bytes hold the free-list links:
  uint64_t next_free;  // file offset of next free block (0 = none)
  uint64_t prev_free;  // file offset of prev free block (0 = none)
};
constexpr uint64_t kBlockHdr = 24;  // size, prev_size, free+pad
constexpr uint64_t kMinBlock = kBlockHdr + 16;

// Per-client pin ledger (ADVICE r1): every pin (creator pin from
// create_object, read pin from get) is recorded under the calling
// process's slot so the node service can reap a crashed worker's pins —
// the analog of plasma releasing a disconnected client's refs
// (reference: plasma store client connection teardown).
struct PinRec {
  uint32_t entry_idx_plus1;  // 0 = empty slot; else table index + 1
  uint32_t count;
  uint64_t id_lo;            // first 8 id bytes: guards against slot reuse
};

struct ClientSlot {
  uint64_t pid;  // 0 = free
  PinRec pins[kClientPinCap];  // open-addressed by entry index
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t data_offset;    // start of allocator region
  uint64_t data_size;
  uint64_t used_bytes;     // payload bytes in live objects
  uint64_t num_objects;
  uint64_t lru_clock;
  uint64_t free_head;      // offset of first free block (0 = none)
  uint64_t num_evictions;
  uint64_t bytes_evicted;
  pthread_mutex_t mutex;
  Entry table[kTableCapacity];
  ClientSlot clients[kMaxClients];
};

struct Store {
  uint8_t* base = nullptr;
  uint64_t size = 0;
  int fd = -1;
  bool in_use = false;
};

constexpr int kMaxStores = 16;
Store g_stores[kMaxStores];
pthread_mutex_t g_stores_mutex = PTHREAD_MUTEX_INITIALIZER;

inline Header* H(Store& s) { return reinterpret_cast<Header*>(s.base); }
inline Block* B(Store& s, uint64_t off) {
  return reinterpret_cast<Block*>(s.base + off);
}

class Locker {
 public:
  explicit Locker(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) {
      // A worker died holding the lock; the segment metadata is still
      // consistent enough for our operations (every mutation below is
      // ordered so a torn update is at worst a leaked block).
      pthread_mutex_consistent(&h_->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

uint64_t hash_id(const uint8_t* id) {
  uint64_t h;
  memcpy(&h, id, 8);
  uint64_t h2;
  memcpy(&h2, id + 8, 8);
  h ^= h2 * 0x9e3779b97f4a7c15ULL;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 33;
  return h;
}

// Find entry for id; returns nullptr if absent.
Entry* find(Header* h, const uint8_t* id) {
  uint64_t idx = hash_id(id) & (kTableCapacity - 1);
  for (uint32_t probe = 0; probe < kTableCapacity; ++probe) {
    Entry& e = h->table[idx];
    if (e.state == kEmpty) return nullptr;
    if (e.state != kTombstone && memcmp(e.id, id, kIdSize) == 0) return &e;
    idx = (idx + 1) & (kTableCapacity - 1);
  }
  return nullptr;
}

// Find slot to insert id (first tombstone or empty); nullptr if table full
// or id already present (then *existing is set).
Entry* insert_slot(Header* h, const uint8_t* id, Entry** existing) {
  *existing = nullptr;
  uint64_t idx = hash_id(id) & (kTableCapacity - 1);
  Entry* slot = nullptr;
  for (uint32_t probe = 0; probe < kTableCapacity; ++probe) {
    Entry& e = h->table[idx];
    if (e.state == kEmpty) {
      return slot ? slot : &e;
    }
    if (e.state == kTombstone) {
      if (!slot) slot = &e;
    } else if (memcmp(e.id, id, kIdSize) == 0) {
      *existing = &e;
      return nullptr;
    }
    idx = (idx + 1) & (kTableCapacity - 1);
  }
  return slot;
}

// ---------------- client pin ledger ----------------

// Find (or claim) the ClientSlot for `pid`. Reclaims slots whose owner
// process is gone. Returns nullptr only when every slot belongs to a
// live process. Caller holds the segment mutex.
ClientSlot* client_slot(Header* h, uint64_t pid) {
  ClientSlot* dead = nullptr;
  ClientSlot* empty = nullptr;
  for (uint32_t i = 0; i < kMaxClients; ++i) {
    ClientSlot& c = h->clients[i];
    if (c.pid == pid) return &c;
    if (c.pid == 0) {
      if (!empty) empty = &c;
    } else if (!dead && kill((pid_t)c.pid, 0) != 0 && errno == ESRCH) {
      dead = &c;
    }
  }
  ClientSlot* slot = empty ? empty : dead;
  if (slot) {
    // NOTE: a reclaimed dead slot may still list unreaped pins; those
    // refcnts stay leaked exactly as before reclamation — reap_client
    // is the supported path.  Zero the ledger for the new owner.
    memset(slot, 0, sizeof(ClientSlot));
    slot->pid = pid;
  }
  return slot;
}

// Add/remove `delta` pins for (pid, entry). Open addressing with
// count==0 tombstones (probe chains end only at entry_idx_plus1==0, so
// decrement-to-zero never breaks lookups of colliding keys).  Records
// carry an id prefix so a table slot recycled for a different object
// never matches a stale record.  Returns true iff the ledger was
// actually updated (false for delta<0 with no matching record —
// the caller may be trying to move a pin that was already reaped).
bool record_pin(Header* h, uint64_t pid, Entry* e, int delta) {
  ClientSlot* c = client_slot(h, pid);
  if (!c) return delta > 0;  // ledger full: untracked (refcnt still correct)
  uint32_t entry_idx = (uint32_t)(e - h->table);
  uint64_t id_lo;
  memcpy(&id_lo, e->id, 8);
  uint32_t key = entry_idx + 1;
  uint32_t idx = entry_idx & (kClientPinCap - 1);
  PinRec* reuse = nullptr;
  for (uint32_t probe = 0; probe < kClientPinCap; ++probe) {
    PinRec& r = c->pins[idx];
    if (r.entry_idx_plus1 == key && (r.count == 0 || r.id_lo == id_lo)) {
      if (delta > 0) {
        r.count += (uint32_t)delta;
        r.id_lo = id_lo;
        return true;
      }
      if (r.count > 0) {
        r.count--;
        return true;
      }
      return false;
    }
    if (r.entry_idx_plus1 == 0) {  // end of probe chain: key absent
      if (delta > 0) {
        PinRec* dst = reuse ? reuse : &r;
        dst->entry_idx_plus1 = key;
        dst->count = (uint32_t)delta;
        dst->id_lo = id_lo;
        return true;
      }
      return false;
    }
    if (r.count == 0 && !reuse) reuse = &r;  // tombstone, reusable
    idx = (idx + 1) & (kClientPinCap - 1);
  }
  if (delta > 0 && reuse) {
    reuse->entry_idx_plus1 = key;
    reuse->count = (uint32_t)delta;
    reuse->id_lo = id_lo;
    return true;
  }
  return delta > 0;
}

void block_free(Store& s, uint64_t off);

// Free an entry's storage. Caller holds the mutex; refcnt must be 0 (or
// the caller is force-resetting a stale CREATING entry).
void entry_free(Store& s, Entry* e) {
  Header* h = H(s);
  h->used_bytes -= e->size;
  h->num_objects--;
  block_free(s, e->offset - kBlockHdr);
  e->state = kTombstone;
  e->refcnt = 0;
  e->pending_delete = 0;
}

// ---------------- allocator ----------------

void freelist_remove(Store& s, uint64_t off) {
  Header* h = H(s);
  Block* b = B(s, off);
  if (b->prev_free) {
    B(s, b->prev_free)->next_free = b->next_free;
  } else {
    h->free_head = b->next_free;
  }
  if (b->next_free) B(s, b->next_free)->prev_free = b->prev_free;
}

void freelist_push(Store& s, uint64_t off) {
  Header* h = H(s);
  Block* b = B(s, off);
  b->free = 1;
  b->next_free = h->free_head;
  b->prev_free = 0;
  if (h->free_head) B(s, h->free_head)->prev_free = off;
  h->free_head = off;
}

uint64_t data_end(Store& s) { return H(s)->data_offset + H(s)->data_size; }

// Free a block at `off`, coalescing with physical neighbors.
void block_free(Store& s, uint64_t off) {
  Block* b = B(s, off);
  // Coalesce with next.
  uint64_t next_off = off + b->size;
  if (next_off < data_end(s)) {
    Block* nb = B(s, next_off);
    if (nb->free) {
      freelist_remove(s, next_off);
      b->size += nb->size;
    }
  }
  // Coalesce with prev.
  if (b->prev_size) {
    uint64_t prev_off = off - b->prev_size;
    Block* pb = B(s, prev_off);
    if (pb->free) {
      freelist_remove(s, prev_off);
      pb->size += b->size;
      off = prev_off;
      b = pb;
    }
  }
  // Fix prev_size of the block after the (possibly grown) free block.
  uint64_t after = off + b->size;
  if (after < data_end(s)) B(s, after)->prev_size = b->size;
  freelist_push(s, off);
}

// Allocate `payload` bytes; returns payload file offset or 0 on failure.
uint64_t block_alloc(Store& s, uint64_t payload) {
  Header* h = H(s);
  uint64_t need = kBlockHdr + payload;
  need = (need + kAlign - 1) & ~(kAlign - 1);
  if (need < kMinBlock) need = kMinBlock;
  uint64_t off = h->free_head;
  while (off) {
    Block* b = B(s, off);
    if (b->size >= need) {
      freelist_remove(s, off);
      b->free = 0;
      uint64_t rem = b->size - need;
      if (rem >= kMinBlock) {
        b->size = need;
        uint64_t rem_off = off + need;
        Block* rb = B(s, rem_off);
        rb->size = rem;
        rb->prev_size = need;
        rb->free = 1;
        uint64_t after = rem_off + rem;
        if (after < data_end(s)) B(s, after)->prev_size = rem;
        freelist_push(s, rem_off);
      }
      return off + kBlockHdr;
    }
    off = b->next_free;
  }
  return 0;
}

// Evict sealed refcnt==0 objects in LRU order until at least `bytes` of
// payload could plausibly be allocated. Returns evicted byte count.
// ONE table scan collects candidates sorted by lru_tick (an insertion
// into a bounded min-heap-ish array) instead of the previous
// O(table * victims) rescan-per-victim, which cliffed at 10k+ objects.
uint64_t evict_lru(Store& s, uint64_t bytes) {
  Header* h = H(s);
  // (lru_tick, index) pairs; sorted ascending so victims pop oldest
  // first.  Heap allocation is fine here: eviction is already the
  // slow path (it only runs when an alloc failed).
  std::vector<std::pair<uint64_t, uint32_t>> cand;
  cand.reserve(256);
  for (uint32_t i = 0; i < kTableCapacity; ++i) {
    Entry& e = h->table[i];
    if (e.state == kSealed && e.refcnt == 0) {
      cand.emplace_back(e.lru_tick, i);
    }
  }
  std::sort(cand.begin(), cand.end());
  uint64_t freed = 0;
  for (size_t j = 0; j < cand.size() && freed < bytes + kBlockHdr;
       ++j) {
    Entry& e = h->table[cand[j].second];
    // Re-check defensively (entry_free of earlier victims cannot
    // change later candidates, but cheap insurance beats corruption).
    if (e.state != kSealed || e.refcnt != 0) continue;
    freed += e.size + kBlockHdr;
    h->num_evictions++;
    h->bytes_evicted += e.size;
    entry_free(s, &e);
  }
  return freed;
}

int get_store(int handle, Store** out) {
  if (handle < 0 || handle >= kMaxStores) return kError;
  Store& s = g_stores[handle];
  if (!s.in_use) return kError;
  *out = &s;
  return kOK;
}

int alloc_handle() {
  pthread_mutex_lock(&g_stores_mutex);
  int h = -1;
  for (int i = 0; i < kMaxStores; ++i) {
    if (!g_stores[i].in_use) {
      g_stores[i].in_use = true;
      h = i;
      break;
    }
  }
  pthread_mutex_unlock(&g_stores_mutex);
  return h;
}

}  // namespace

extern "C" {

// Create a new store file of `size` bytes at `path` and initialize it.
int shm_store_create(const char* path, uint64_t size) {
  if (size < sizeof(Header) + (1 << 20)) return kError;
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return kError;
  if (ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    unlink(path);
    return kError;
  }
  void* base =
      mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    unlink(path);
    return kError;
  }
  int handle = alloc_handle();
  if (handle < 0) {
    munmap(base, size);
    close(fd);
    unlink(path);
    return kError;
  }
  Store& s = g_stores[handle];
  s.base = static_cast<uint8_t*>(base);
  s.size = size;
  s.fd = fd;

  Header* h = H(s);
  memset(h, 0, sizeof(Header));
  h->total_size = size;
  h->data_offset = (sizeof(Header) + kAlign - 1) & ~(kAlign - 1);
  h->data_size = size - h->data_offset;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // One giant free block spanning the data region.
  Block* b = B(s, h->data_offset);
  b->size = h->data_size & ~(kAlign - 1);
  b->prev_size = 0;
  b->free = 1;
  b->next_free = 0;
  b->prev_free = 0;
  h->free_head = h->data_offset;

  __sync_synchronize();
  h->magic = kMagic;  // publish: openers spin on this
  return handle;
}

int shm_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return kError;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return kError;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return kError;
  }
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic) {
    munmap(base, st.st_size);
    close(fd);
    return kError;
  }
  int handle = alloc_handle();
  if (handle < 0) {
    munmap(base, st.st_size);
    close(fd);
    return kError;
  }
  g_stores[handle].base = static_cast<uint8_t*>(base);
  g_stores[handle].size = st.st_size;
  g_stores[handle].fd = fd;
  return handle;
}

int shm_store_close(int handle) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  munmap(s->base, s->size);
  close(s->fd);
  s->base = nullptr;
  s->fd = -1;
  pthread_mutex_lock(&g_stores_mutex);
  s->in_use = false;
  pthread_mutex_unlock(&g_stores_mutex);
  return kOK;
}

// Begin creating an object: allocates space (evicting if needed), marks it
// CREATING with refcnt 1 (held by the creator), returns payload offset.
int shm_store_create_object(int handle, const uint8_t* id, uint64_t size,
                            uint64_t* offset_out) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* existing;
  Entry* slot = insert_slot(h, id, &existing);
  if (existing) return kExists;
  if (!slot) return kTableFull;
  uint64_t off = block_alloc(*s, size);
  if (!off) {
    evict_lru(*s, size);
    off = block_alloc(*s, size);
    if (!off) return kFull;
  }
  memcpy(slot->id, id, kIdSize);
  slot->offset = off;
  slot->size = size;
  slot->state = kStateCreating;
  slot->refcnt = 1;
  slot->pending_delete = 0;
  slot->creator_pid = (uint32_t)getpid();
  slot->lru_tick = ++h->lru_clock;
  h->used_bytes += size;
  h->num_objects++;
  record_pin(h, (uint64_t)getpid(), slot, +1);
  *offset_out = off;
  return kOK;
}

int shm_store_seal(int handle, const uint8_t* id) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (e->state != kStateCreating) return kError;
  e->state = kSealed;
  return kOK;
}

// Abort a creation (failed write): frees the allocation.
int shm_store_abort(int handle, const uint8_t* id) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (e->state != kStateCreating) return kError;
  record_pin(h, (uint64_t)getpid(), e, -1);
  entry_free(*s, e);
  return kOK;
}

// Get a sealed object: bumps refcnt (pin) and LRU tick.
int shm_store_get(int handle, const uint8_t* id, uint64_t* offset_out,
                  uint64_t* size_out) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (e->state == kStateCreating) return kCreating;
  e->refcnt++;
  e->lru_tick = ++h->lru_clock;
  record_pin(h, (uint64_t)getpid(), e, +1);
  *offset_out = e->offset;
  *size_out = e->size;
  return kOK;
}

int shm_store_contains(int handle, const uint8_t* id) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  return (e && e->state == kSealed) ? 1 : 0;
}

// Release a pin taken by get (or by create after seal).
int shm_store_release(int handle, const uint8_t* id) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (e->refcnt > 0) {
    e->refcnt--;
    record_pin(h, (uint64_t)getpid(), e, -1);
  }
  if (e->refcnt == 0 && e->pending_delete) entry_free(*s, e);
  return kOK;
}

// Delete an object (frees immediately if unpinned, else when released).
int shm_store_delete(int handle, const uint8_t* id) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (e->refcnt > 0) {
    e->pending_delete = 1;
    return kOK;
  }
  entry_free(*s, e);
  return kOK;
}

// Move one pin of `id` from `from_pid`'s ledger to `to_pid`'s (refcnt
// unchanged).  Used by the node service to ADOPT a worker's creator pin
// when it registers a sealed shm object in the directory — so reaping
// the worker later does not release directory-owned pins.  Returns
// kNoPin when from_pid holds no recorded pin (e.g. it was already
// reaped): the caller must then acquire its own pin instead.
int shm_store_transfer_pin(int handle, const uint8_t* id,
                           uint64_t from_pid, uint64_t to_pid) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (from_pid == to_pid) return kOK;
  if (!record_pin(h, from_pid, e, -1)) return kNoPin;
  record_pin(h, to_pid, e, +1);
  return kOK;
}

// Release every pin recorded for `pid` (a dead client).  CREATING
// entries whose creator died are freed outright.  Returns the number of
// pins released, or a status code (<0) on error.
int shm_store_reap_client(int handle, uint64_t pid) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  ClientSlot* c = nullptr;
  for (uint32_t i = 0; i < kMaxClients; ++i) {
    if (h->clients[i].pid == pid) {
      c = &h->clients[i];
      break;
    }
  }
  if (!c) return 0;
  int released = 0;
  for (uint32_t i = 0; i < kClientPinCap; ++i) {
    PinRec& r = c->pins[i];
    if (r.entry_idx_plus1 == 0 || r.count == 0) continue;
    Entry& e = h->table[r.entry_idx_plus1 - 1];
    uint64_t id_lo;
    memcpy(&id_lo, e.id, 8);
    if (id_lo != r.id_lo) continue;  // table slot was recycled: stale rec
    if (e.state == kSealed || e.state == kStateCreating) {
      uint32_t n = r.count < e.refcnt ? r.count : e.refcnt;
      e.refcnt -= n;
      released += (int)n;
      if (e.refcnt == 0) {
        if (e.state == kStateCreating) {
          entry_free(*s, &e);  // half-written object from a crashed worker
        } else if (e.pending_delete) {
          entry_free(*s, &e);
        }
      }
    }
  }
  memset(c, 0, sizeof(ClientSlot));
  return released;
}

// Force-free a leftover entry from a CRASHED prior task attempt (either
// half-written CREATING, or sealed-but-never-registered).  Refuses when
// the creating process is still alive — it may be mid-write, and
// freeing under it would let its stores corrupt a reallocated block.
int shm_store_reset_stale(int handle, const uint8_t* id) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  Entry* e = find(h, id);
  if (!e) return kNotFound;
  if (e->state != kStateCreating && e->state != kSealed) return kError;
  if (e->creator_pid && kill((pid_t)e->creator_pid, 0) == 0) {
    return kError;  // creator alive (or EPERM): not stale
  }
  entry_free(*s, e);
  return kOK;
}

int shm_store_stats(int handle, uint64_t* used, uint64_t* capacity,
                    uint64_t* num_objects, uint64_t* num_evictions) {
  Store* s;
  if (get_store(handle, &s) != kOK) return kError;
  Header* h = H(*s);
  Locker lock(h);
  *used = h->used_bytes;
  *capacity = h->data_size;
  *num_objects = h->num_objects;
  *num_evictions = h->num_evictions;
  return kOK;
}

}  // extern "C"
