"""Lazy native build: compiles the C++ runtime libraries with g++ on first
use and caches the .so next to the sources (rebuilds when sources are newer).

The reference ships prebuilt bazel artifacts; we compile at import time so
the repo needs no install step.
"""

from __future__ import annotations

import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()

_CXX = os.environ.get("CXX", "g++")
_FLAGS = ["-O2", "-g", "-fPIC", "-shared", "-std=c++17", "-pthread", "-Wall"]


def build_library(name: str, sources: list[str]) -> str:
    """Compile `sources` (relative to native/) into lib<name>.so; returns
    the .so path. No-op when the cached .so is newer than all sources."""
    so_path = os.path.join(_NATIVE_DIR, f"lib{name}.so")
    srcs = [os.path.join(_NATIVE_DIR, s) for s in sources]
    with _LOCK:
        if os.path.exists(so_path):
            so_mtime = os.path.getmtime(so_path)
            if all(os.path.getmtime(s) <= so_mtime for s in srcs):
                return so_path
        tmp = so_path + f".tmp.{os.getpid()}"
        cmd = [_CXX, *_FLAGS, "-o", tmp, *srcs]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{e.stderr}") from e
        os.replace(tmp, so_path)
    return so_path
