"""Lazy native build: compiles the C++ runtime libraries with g++ on first
use and caches the .so next to the sources.

Cache validity is decided by a content hash of the sources + compile
flags (written to lib<name>.so.hash), not mtimes — a fresh checkout gives
every file the same mtime, which would silently keep a stale or
wrong-arch binary (ADVICE r1).  The reference ships prebuilt bazel
artifacts; we compile at import time so the repo needs no install step.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()

_CXX = os.environ.get("CXX", "g++")
_FLAGS = ["-O2", "-g", "-fPIC", "-shared", "-std=c++17", "-pthread", "-Wall"]


def _content_hash(srcs: list[str]) -> str:
    h = hashlib.sha256()
    h.update(" ".join([_CXX] + _FLAGS).encode())
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def build_library(name: str, sources: list[str], force: bool = False) -> str:
    """Compile `sources` (relative to native/) into lib<name>.so; returns
    the .so path.  No-op when the cached .so matches the source hash."""
    so_path = os.path.join(_NATIVE_DIR, f"lib{name}.so")
    hash_path = so_path + ".hash"
    srcs = [os.path.join(_NATIVE_DIR, s) for s in sources]
    with _LOCK:
        want = _content_hash(srcs)
        if not force and os.path.exists(so_path):
            try:
                with open(hash_path) as f:
                    if f.read().strip() == want:
                        return so_path
            except OSError:
                pass
        tmp = so_path + f".tmp.{os.getpid()}"
        cmd = [_CXX, *_FLAGS, "-o", tmp, *srcs]
        try:
            # Serializing concurrent builds of the same .so is the
            # lock's entire job — waiters NEED to block until the
            # compile finishes.
            subprocess.run(cmd, check=True, capture_output=True,  # ray-tpu: noqa[RT011]
                           text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{e.stderr}") from e
        os.replace(tmp, so_path)
        with open(hash_path + f".tmp.{os.getpid()}", "w") as f:
            f.write(want)
        os.replace(hash_path + f".tmp.{os.getpid()}", hash_path)
    return so_path
