"""`python -m ray_tpu <command>`: the cluster CLI.

Reference surface: python/ray/scripts/scripts.py (`ray start/stop/
status`) + `ray list/summary` (util/state CLI) + `ray job` (job CLI).

    start --head [...]        start GCS + head node + dashboard, detached
    start --address H:P       join an existing cluster as a worker node
    stop                      stop every process this CLI started
    drain <node_id> [--grace S]
                              gracefully drain a node (planned departure)
    status [--address H:P]    cluster nodes + resources
    list {tasks,actors,workers,objects,nodes,pgs}
    summary                   task/actor/object rollups
    memory [--group-by node|owner] [--leak-suspects]
                              cluster memory accounting: object bytes
                              by reference kind/owner/node vs real shm
                              store usage, plus leak suspects
    stack [task_id] [--flame] cluster-wide worker stack dumps; target
                              one task, or sample into a flamegraph
    metrics                   Prometheus text from the head
    job {submit,status,logs,list,stop}
    microbench                core-runtime perf harness
    lint <path>...            static analysis (RT001-RT020) for
                              remote/actor/sharding/concurrency/
                              lifecycle/XLA code (--lock-graph dumps
                              the lock-order graph; --changed lints
                              only git-modified files)
    locksan                   merged runtime lock-sanitizer report
                              from a RAY_TPU_LOCKSAN=1 run
    leaksan                   merged resource-leak ledger from a
                              RAY_TPU_LEAKSAN=1 run (exit 1 on leaks)
    xlasan                    merged XLA recompile/host-sync ledger
                              from a RAY_TPU_XLASAN=1 run (exit 1 on
                              recompile storms over budget)
    doctor                    cluster health triage: GCS liveness/WAL,
                              stalls, slow RPCs, leak suspects,
                              event-ring drops, serve shedding, train
                              goodput — prioritized findings with
                              stable codes; exit 1 on errors
    top [--interval S]        live terminal view over the metrics
                              history rings (runtime gauges + busiest
                              RPC handlers, sparklines)
    bench-diff NEW BASE       direction-aware bench-capture regression
                              gate (exit 1 when a throughput metric
                              drops / latency metric rises beyond
                              --tolerance)

State (started pids, head address) persists in ~/.ray_tpu_cli.json so
`stop`/`status` work from a fresh shell."""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

STATE_PATH = os.path.expanduser("~/.ray_tpu_cli.json")


# ---------------------------------------------------------------------------
# CLI state file
# ---------------------------------------------------------------------------
def _load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"procs": []}


def _save_state(st: dict) -> None:
    with open(STATE_PATH, "w") as f:
        json.dump(st, f, indent=1)


def _daemon_log(role: str) -> str:
    d = os.path.expanduser("~/.ray_tpu_logs")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{role}-{int(time.time())}.err")


def _parse_addr(addr: str) -> tuple:
    host, _, port = addr.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _head_address(args) -> Optional[str]:
    if getattr(args, "address", None):
        return args.address
    st = _load_state()
    return st.get("gcs_address")


# ---------------------------------------------------------------------------
# start / stop / status
# ---------------------------------------------------------------------------
def cmd_start(args) -> int:
    st = _load_state()
    env = dict(os.environ)
    if args.head:
        cmd = [sys.executable, "-m", "ray_tpu.scripts.head",
               "--host", args.host, "--port", str(args.port),
               "--dashboard-port", str(args.dashboard_port),
               "--resources", args.resources]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        if args.object_store_memory:
            cmd += ["--object-store-memory",
                    str(args.object_store_memory)]
        if args.persist_dir:
            cmd += ["--persist-dir", args.persist_dir]
        err_f = open(_daemon_log("head"), "ab")
        try:
            # stderr to a log file, NOT inherited: a detached daemon
            # holding the caller's pipe would hang any capture of this
            # CLI's own output.
            proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                    stderr=err_f, text=True,
                                    start_new_session=True)
        finally:
            err_f.close()
        info = _await_line(proc, "HEAD_READY=", args.timeout)
        head = json.loads(info)
        st["gcs_address"] = head["gcs_address"]
        st["dashboard_url"] = head["dashboard_url"]
        st["client_address"] = head.get("client_address")
        st["procs"].append({"pid": proc.pid, "role": "head"})
        _save_state(st)
        print(f"head started: gcs={head['gcs_address']} "
              f"client={head.get('client_address')} "
              f"dashboard={head['dashboard_url']} pid={proc.pid}")
        print(f"join with: python -m ray_tpu start "
              f"--address {head['gcs_address']}")
        return 0
    addr = args.address or st.get("gcs_address")
    if not addr:
        print("error: --address required (no head on record)",
              file=sys.stderr)
        return 1
    host, port = _parse_addr(addr)
    cmd = [sys.executable, "-m", "ray_tpu._private.node_service",
           "--gcs-host", host, "--gcs-port", str(port),
           "--resources", args.resources]
    if args.object_store_memory:
        cmd += ["--store-capacity", str(args.object_store_memory)]
    err_f = open(_daemon_log("node"), "ab")
    try:
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=err_f, text=True,
                                start_new_session=True)
    finally:
        err_f.close()
    node_id = _await_line(proc, "NODE_READY=", args.timeout)
    st["procs"].append({"pid": proc.pid, "role": "node"})
    _save_state(st)
    print(f"node {node_id[:12]} joined {addr} (pid={proc.pid})")
    return 0


def _await_line(proc, prefix: str, timeout_s: float) -> str:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"process exited early (rc={proc.poll()})")
        if line.startswith(prefix):
            # Leave the pipe to the OS; the daemon keeps running.
            import threading

            def drain(p=proc.stdout):
                try:
                    for _ in p:
                        pass
                except (OSError, ValueError):
                    pass
            threading.Thread(target=drain, daemon=True).start()
            return line.strip()[len(prefix):]
    proc.kill()
    raise TimeoutError(f"no {prefix} within {timeout_s}s")


def cmd_stop(args) -> int:
    st = _load_state()
    stopped = 0
    for rec in st.get("procs", []):
        try:
            os.killpg(os.getpgid(rec["pid"]), signal.SIGTERM)
            stopped += 1
        except (ProcessLookupError, PermissionError):
            pass
    _save_state({"procs": []})
    print(f"stopped {stopped} process group(s)")
    return 0


def cmd_status(args) -> int:
    addr = _head_address(args)
    if not addr:
        print("no cluster on record (start one with: "
              "python -m ray_tpu start --head)", file=sys.stderr)
        return 1
    from ray_tpu._private.gcs_service import GcsClient
    host, port = _parse_addr(addr)
    gcs = GcsClient(host, port)
    try:
        nodes = gcs.nodes()
    finally:
        gcs.close()
    print(f"cluster at {addr}: {len(nodes)} node(s)")
    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for n in nodes:
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in n["resources_avail"].items():
            avail[k] = avail.get(k, 0.0) + v
        print(f"  node {n['node_id'].hex()[:12]} {n['host']} "
              f"state={n.get('state', 'alive')}")
    for k in sorted(total):
        print(f"  {avail.get(k, 0.0):g}/{total[k]:g} {k}")
    return 0


# ---------------------------------------------------------------------------
# state queries (served by the head's dashboard HTTP endpoints)
# ---------------------------------------------------------------------------
def _dashboard_url(args) -> str:
    st = _load_state()
    url = getattr(args, "dashboard_url", None) or st.get("dashboard_url")
    if not url:
        raise SystemExit("no dashboard on record; pass --dashboard-url")
    if "://" not in url:
        url = f"http://{url}"
    return url


def _fetch_json(path: str, args) -> Any:
    url = _dashboard_url(args)
    with urllib.request.urlopen(f"{url}{path}", timeout=30) as r:
        return json.loads(r.read())


def _print_table(rows: List[dict], cols: List[str]) -> None:
    if not rows:
        print("(empty)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c])
                        for c in cols))


def cmd_list(args) -> int:
    dump = _fetch_json("/api/state", args)
    kind = args.kind
    key = {"tasks": "tasks", "actors": "actors", "workers": "workers",
           "objects": "objects", "pgs": "placement_groups",
           "nodes": "nodes"}[kind]
    rows = dump.get(key) or []
    cols = {
        "tasks": ["task_id", "name", "state", "pid", "retries_left"],
        "actors": ["actor_id", "class_name", "name", "state", "pid"],
        "workers": ["worker_id", "pid", "state", "tpu", "task"],
        "objects": ["object_id", "state", "loc", "size", "refcount"],
        "pgs": ["pg_id", "name", "strategy", "state"],
        "nodes": ["node_id", "host", "state"],
    }[kind]
    for r in rows:
        for c in cols:
            if isinstance(r.get(c), bytes):
                r[c] = r[c].hex()
        for c in ("task_id", "actor_id", "worker_id", "object_id",
                  "pg_id", "node_id"):
            if isinstance(r.get(c), str) and len(r[c]) > 16:
                r[c] = r[c][:16]
    _print_table(rows, cols)
    return 0


def cmd_summary(args) -> int:
    print(json.dumps(_fetch_json("/api/summary", args), indent=1,
                     default=str))
    return 0


def _fmt_bytes(n: float) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024
    return f"{n:.1f}TiB"


def cmd_memory(args) -> int:
    """Cluster memory accounting (reference: `ray memory`): per-node
    object-store breakdown by reference kind (owned / borrowed /
    pinned_by_actor / spilled / drain_replica) and by owner, next to
    each node's real shm store usage; --leak-suspects flags old
    objects whose owner client is dead or whose borrow count is
    zero."""
    summary = _fetch_json(
        f"/api/memory?min_age_s={args.min_age_s:g}", args)
    print(f"cluster objects: {summary.get('object_count', 0)} ready, "
          f"{_fmt_bytes(summary.get('total_bytes', 0))}")
    for kind, cell in sorted((summary.get("by_kind") or {}).items()):
        print(f"  {kind}: {cell['count']} objects, "
              f"{_fmt_bytes(cell['bytes'])}")
    kv = summary.get("kv_blocks") or {}
    if kv:
        parts = " ".join(f"{s}={int(kv.get(s, 0))}"
                         for s in ("used", "cached", "free"))
        print(f"paged-KV blocks (serve LLM engines): {parts}")
    group = getattr(args, "group_by", "node")
    if group == "owner":
        rows = [{"owner": (o[:16] if isinstance(o, str) else o),
                 "objects": c["count"],
                 "bytes": _fmt_bytes(c["bytes"])}
                for o, c in sorted((summary.get("by_owner") or {})
                                   .items(),
                                   key=lambda kv: -kv[1]["bytes"])]
        print("\nby owner:")
        _print_table(rows, ["owner", "objects", "bytes"])
    else:
        rows = []
        for nid, c in sorted((summary.get("by_node") or {}).items()):
            rows.append({
                "node": nid[:12],
                "objects": c.get("count", 0),
                "bytes": _fmt_bytes(c.get("bytes", 0)),
                "store_used": _fmt_bytes(c.get("store_used_bytes", 0)),
                "store_capacity": _fmt_bytes(
                    c.get("store_capacity_bytes", 0)),
            })
        print("\nby node:")
        _print_table(rows, ["node", "objects", "bytes", "store_used",
                            "store_capacity"])
    if getattr(args, "leak_suspects", False):
        suspects = summary.get("leak_suspects") or []
        print(f"\nleak suspects ({len(suspects)}):")
        rows = [{"object_id": s.get("object_id", "")[:16],
                 "node": (s.get("node_id") or "")[:12],
                 "kind": s.get("reference_kind"),
                 "bytes": _fmt_bytes(s.get("size_bytes", 0)),
                 "age_s": s.get("age_s"),
                 "reason": s.get("leak_reason")}
                for s in suspects]
        _print_table(rows, ["object_id", "node", "kind", "bytes",
                            "age_s", "reason"])
    unreachable = summary.get("unreachable_nodes") or []
    if unreachable:
        print(f"\nWARNING: partial snapshot — unreachable nodes: "
              f"{', '.join(n[:12] for n in unreachable)}")
    return 0


def cmd_timeline(args) -> int:
    """Chrome-trace export of the runtime timeline (open the file in
    chrome://tracing or Perfetto; reference: `ray timeline`)."""
    events = _fetch_json("/api/timeline", args)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(events, f)
        print(f"wrote {len(events)} events to {args.out}")
    else:
        print(json.dumps(events, indent=1, default=str))
    return 0


def cmd_metrics(args) -> int:
    url = _dashboard_url(args)
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        sys.stdout.write(r.read().decode())
    return 0


# ---------------------------------------------------------------------------
# jobs
# ---------------------------------------------------------------------------
def _job_client(args):
    from ray_tpu.util.job import JobSubmissionClient
    addr = _head_address(args)
    if not addr:
        raise SystemExit("no cluster on record")
    return JobSubmissionClient(addr)


def cmd_stack(args) -> int:
    """On-demand stack dump of every live worker in the cluster
    (reference: `ray stack` / the dashboard's py-spy role), served by
    the head's dashboard.  With a task_id hex prefix, dumps only the
    worker(s) executing that task; --flame switches to low-rate stack
    sampling merged into flamegraph.pl folded format."""
    if args.flame:
        url = _dashboard_url(args) + (
            f"/api/flamegraph?samples={args.samples}"
            f"&interval_s={args.interval:g}")
        if args.task_id:
            url += f"&task_id={args.task_id}"
        # The server blocks for the whole sampling window — scale the
        # HTTP timeout with it instead of racing a fixed constant.
        http_timeout = args.samples * args.interval + 60.0
        with urllib.request.urlopen(url, timeout=http_timeout) as r:
            folded = r.read().decode()
        if args.out:
            with open(args.out, "w") as f:
                f.write(folded + ("\n" if folded else ""))
            print(f"wrote folded stacks to {args.out} "
                  f"(render with flamegraph.pl or speedscope)")
        else:
            print(folded if folded else "(no samples collected)")
        return 0
    path = f"/api/stack?timeout={args.timeout:g}"
    if args.task_id:
        path += f"&task_id={args.task_id}"
    # Dashboard + node fanout wait up to args.timeout (+5s margin
    # each) before replying — outlast them.
    url = _dashboard_url(args)
    with urllib.request.urlopen(f"{url}{path}",
                                timeout=args.timeout + 30.0) as r:
        stacks = (json.loads(r.read()) or {}).get("stacks") or {}
    if not stacks:
        print("no matching live workers" if args.task_id
              else "no live workers")
        return 1 if args.task_id else 0
    for pid, text in sorted(stacks.items(), key=lambda kv: str(kv[0])):
        print(f"===== worker {pid} =====")
        print(text)
    return 0


def _serve_shed_counters() -> dict:
    """deployment -> {reason: count} from the merged metric plane."""
    out: dict = {}
    try:
        from ray_tpu.util import metrics
        for s in metrics.scrape():
            if s.get("name") != metrics.SERVE_REQUESTS_SHED_METRIC:
                continue
            tags = s.get("tags") or {}
            dep = tags.get("deployment", "?")
            out.setdefault(dep, {})[tags.get("reason", "?")] = \
                int(s.get("value") or 0)
    except Exception:
        pass
    return out


def _render_serve_status(data: dict, shed: dict) -> str:
    """Text face of `ray_tpu serve status` (pure: unit-testable).
    `data` is the controller's overload_status(); `shed` maps
    deployment -> {reason: count} from the metric plane."""
    lines = []
    for name, s in sorted(data.items()):
        lines.append(
            f"{name}: {s.get('running', 0)} running"
            f" / {s.get('draining', 0)} draining"
            f" (target {s.get('target_replicas', '?')},"
            f" v{s.get('version', '?')})")
        qd = s.get("queue_depth")
        ttft = s.get("ttft_p95_ms")
        itl = s.get("itl_p95_ms")
        lines.append(
            "  queue_depth "
            + (f"{qd:g}" if qd is not None else "n/a")
            + "  ttft_p95 "
            + (f"{ttft:.1f}ms" if ttft is not None else "n/a")
            + "  itl_p95 "
            + (f"{itl:.2f}ms" if itl is not None else "n/a"))
        counts = shed.get(name) or {}
        if counts:
            lines.append("  shed: " + ", ".join(
                f"{r}={n}" for r, n in sorted(counts.items())))
        adm = s.get("admission")
        if adm:
            lines.append("  admission: " + ", ".join(
                f"{k}={v}" for k, v in sorted(adm.items())))
        last = s.get("autoscale_last")
        if last:
            lines.append(
                f"  autoscale: {last.get('action')} "
                f"{last.get('current')} -> {last.get('desired')} "
                f"({last.get('reason')})")
        for ev in s.get("autoscale_events") or []:
            lines.append(
                f"    event: {ev.get('action')} {ev.get('current')}"
                f" -> {ev.get('desired')} ({ev.get('reason')})")
    return "\n".join(lines) if lines else "(no deployments)"


def _render_train_status(data: dict) -> str:
    """Text face of `ray_tpu train status` (pure: unit-testable).
    `data` is state.train_summary()'s {"runs": {...}} payload."""
    runs = data.get("runs") or {}
    if not runs:
        return "(no train runs recorded)"
    lines = []
    for name, r in sorted(runs.items()):
        lines.append(
            f"run {name} [{r.get('state', '?')}]: "
            f"step {r.get('step_index', 0)}, "
            f"{r.get('workers_reporting', 0)}"
            f"/{r.get('world_size', '?')} workers, "
            f"wall {float(r.get('wall_s') or 0):.1f}s, "
            f"restarts {r.get('restarts', 0)}"
            + (f", resizes {r.get('resize_count')}"
               if r.get("resize_count") else ""))
        lines.append(f"  verdict: {r.get('verdict', 'n/a')}")
        # Elastic resize history (train/elastic.py): direction,
        # world-size transition, the checkpoint step resharded from,
        # and the dead time the resize charged to resize_recovery.
        for ev in (r.get("resizes") or [])[-6:]:
            lines.append(
                f"  resize {ev.get('direction', '?')}: "
                f"{ev.get('from', '?')} -> {ev.get('to', '?')} workers"
                f" @ ckpt step {ev.get('step', '?')}"
                f" (+{float(ev.get('dead_s') or 0):.2f}s dead)")
        cr = r.get("ckpt_reads") or {}
        if any(int(v or 0) for v in cr.values()):
            lines.append(
                f"  ckpt restores: memory={int(cr.get('memory') or 0)}"
                f" disk={int(cr.get('disk') or 0)}")
        tok = float(r.get("tokens_per_s") or 0.0)
        mfu = r.get("mfu")
        line = f"  tokens/s {tok:,.0f}"
        if mfu is not None:
            line += f"  MFU {float(mfu):.3f}"
        sm = r.get("step_ms") or {}
        line += (f"  step p50 {float(sm.get('p50') or 0):.1f}ms"
                 f" p95 {float(sm.get('p95') or 0):.1f}ms")
        lines.append(line)
        phases = r.get("phases") or {}
        if phases:
            lines.append("  phases: " + "  ".join(
                f"{p}={c.get('seconds', 0):.2f}s"
                f"({float(c.get('fraction') or 0) * 100:.0f}%)"
                for p, c in phases.items()
                if float(c.get("seconds") or 0) > 0))
        ledger = r.get("ledger") or {}
        lines.append(
            "  goodput ledger: " + "  ".join(
                f"{c}={v:.2f}s" for c, v in ledger.items()
                if float(v or 0) > 0)
            + f"  (coverage {float(r.get('coverage') or 0) * 100:.0f}%"
              f", goodput "
              f"{float(r.get('goodput_fraction') or 0) * 100:.0f}%)")
        flagged = {rk: v for rk, v in
                   (r.get("stragglers") or {}).items()
                   if v.get("straggler")}
        for rk, v in sorted(flagged.items(),
                            key=lambda kv: int(kv[0])
                            if str(kv[0]).isdigit() else 0):
            p95 = float(v.get("p95_s") or 0.0)
            med = float(v.get("median_s") or 0.0)
            lines.append(
                f"  STRAGGLER rank {rk}: step p95 "
                f"{p95 * 1000:.1f}ms vs gang median "
                f"{med * 1000:.1f}ms"
                + (" (stack captured)"
                   if rk in (r.get("straggler_captures") or {})
                   else ""))
    return "\n".join(lines)


def cmd_train(args) -> int:
    """Training telemetry status (train/telemetry.py): per-run step
    decomposition, live MFU + tokens/s, goodput ledger, and
    straggler verdicts, served by the head's dashboard."""
    path = "/api/train"
    if getattr(args, "run", None):
        from urllib.parse import quote
        path += f"?run={quote(args.run, safe='')}"
    try:
        data = _fetch_json(path, args)
    except urllib.error.HTTPError as e:
        # An unknown --run surfaces as the dashboard's 500 payload;
        # show the server's error (it names the known runs) instead
        # of a urllib traceback.
        try:
            detail = json.loads(e.read()).get("error", str(e))
        except Exception:
            detail = str(e)
        print(f"error: {detail}", file=sys.stderr)
        return 1
    if getattr(args, "run", None):
        data = {"runs": {args.run: data}}
    if getattr(args, "json", False):
        print(json.dumps(data, indent=1, default=str))
    else:
        print(_render_train_status(data))
    return 0


def cmd_serve(args) -> int:
    """Declarative serve apply/status/shutdown (reference: `serve
    deploy` over the REST config, serve/schema.py)."""
    from ray_tpu.util import client as thin
    addr = getattr(args, "address", None) or _head_address(args)
    if not addr:
        raise SystemExit("no cluster on record; pass --address H:P")
    ctx = thin.connect(addr)
    try:
        from ray_tpu import serve
        if args.serve_cmd == "deploy":
            from ray_tpu.serve.schema import serve_apply
            names = serve_apply(args.config)
            print(json.dumps({"deployed": names}))
        elif args.serve_cmd == "status":
            import ray_tpu
            from ray_tpu.serve._controller import CONTROLLER_NAME
            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME)
                data = ray_tpu.get(
                    controller.overload_status.remote(), timeout=60)
            except ValueError:
                data = {}       # serve never started on this cluster
            shed = _serve_shed_counters()
            if getattr(args, "json", False):
                print(json.dumps({"deployments": data, "shed": shed},
                                 indent=1, default=str))
            else:
                print(_render_serve_status(data, shed))
        elif args.serve_cmd == "shutdown":
            serve.shutdown()
            print("serve shut down")
    finally:
        ctx.disconnect()
    return 0


def cmd_job(args) -> int:
    jc = _job_client(args)
    try:
        if args.job_cmd == "submit":
            import shlex
            argv = args.entrypoint
            if argv and argv[0] == "--":
                argv = argv[1:]
            entrypoint = shlex.join(argv)
            job_id = jc.submit_job(
                entrypoint=entrypoint,
                runtime_env=({"working_dir": args.working_dir}
                             if args.working_dir else None))
            print(f"submitted {job_id}")
            if args.wait:
                status = jc.wait(job_id)
                print(f"{job_id}: {status}")
                sys.stdout.write(jc.get_job_logs(job_id))
                return 0 if status == "SUCCEEDED" else 1
        elif args.job_cmd == "status":
            print(jc.get_job_status(args.job_id))
        elif args.job_cmd == "logs":
            sys.stdout.write(jc.get_job_logs(args.job_id))
        elif args.job_cmd == "list":
            _print_table(jc.list_jobs(),
                         ["job_id", "status", "entrypoint"])
        elif args.job_cmd == "stop":
            jc.stop_job(args.job_id)
            print(f"stopped {args.job_id}")
        return 0
    finally:
        jc.close()


def cmd_microbench(args) -> int:
    from ray_tpu.util.microbench import run_all
    run_all()
    return 0


def cmd_lint(args) -> int:
    from ray_tpu.devtools.lint import cli as lint_cli
    return lint_cli.run(args)


def cmd_locksan(args) -> int:
    """Merged runtime lock-sanitizer report (devtools/locksan.py).
    Run the workload with RAY_TPU_LOCKSAN=1 first; every process
    drops a <pid>.json report into the locksan dir.  Exit 1 when any
    lock-order inversion was witnessed, 0 on a clean run."""
    from ray_tpu.devtools import locksan
    rep = locksan.merged_report(args.dir)
    if args.json:
        print(json.dumps(rep, indent=1, default=str))
        return 1 if rep["inversions"] else 0
    print(f"locksan report ({rep['processes']} process(es), "
          f"{rep['acquires']} tracked acquires, dir "
          f"{args.dir or locksan.report_dir()})")
    if not rep["processes"]:
        print("no reports found — run the workload with "
              "RAY_TPU_LOCKSAN=1")
        return 0
    inv = rep["inversions"]
    print(f"\nlock-order inversions: {len(inv)}")
    for i in inv:
        print(f"  {i.get('order_here')}  (reverse order seen "
              f"earlier; thread {i.get('thread')}, pid "
              f"{i.get('pid')})")
        for ln in (i.get("stack_here") or [])[-4:]:
            print(f"    {ln}")
    holds = rep["long_holds"]
    print(f"\nlong holds (> lock_hold_warn_ms): {len(holds)}")
    for h in holds[:10]:
        print(f"  {h.get('held_s'):>8}s  {h.get('site')}  "
              f"(thread {h.get('thread')}, pid {h.get('pid')})")
    same = rep.get("same_site_nesting") or {}
    if same:
        print(f"\nsame-site lock nesting (direction not checkable "
              f"by site — verify instance ordering): {len(same)}")
        for site, cell in sorted(same.items(),
                                 key=lambda kv: -kv[1]["count"]):
            print(f"  x{cell['count']}  {site}")
    cont = sorted(rep["contention"].items(), key=lambda kv: -kv[1])
    print(f"\nmost contended lock sites:")
    for site, n in cont[:10]:
        print(f"  {n:>6}  {site}")
    if not cont:
        print("  (no contention observed)")
    return 1 if inv else 0


def cmd_leaksan(args) -> int:
    """Merged resource-leak ledger (devtools/leaksan.py).  Run the
    workload with RAY_TPU_LEAKSAN=1 first; every process drops a
    <pid>.json ledger into the leaksan dir at exit.  Anything still
    live in a ledger at dump time was never released — exit 1 on any
    leak or exactly-once anomaly, 0 on a clean run."""
    from ray_tpu.devtools import leaksan
    rep = leaksan.merged_report(args.dir)
    bad = bool(rep["leaks"] or rep["anomalies"])
    if args.json:
        print(json.dumps(rep, indent=1, default=str))
        return 1 if bad else 0
    print(f"leaksan report ({rep['processes']} process(es), "
          f"{rep['registrations']} tracked registrations, dir "
          f"{args.dir or leaksan.report_dir()})")
    if not rep["processes"]:
        print("no ledgers found — run the workload with "
              "RAY_TPU_LEAKSAN=1")
        return 0
    print("\nper-kind registered/discharged:")
    for kind in sorted(rep["registered"]):
        reg = rep["registered"][kind]
        dis = rep["discharged"].get(kind, 0)
        leaked = rep["leak_counts"].get(kind, 0)
        mark = f"  LEAKED {leaked}" if leaked else ""
        print(f"  {kind:<16} {reg:>8} / {dis:<8}{mark}")
    print(f"\nleaked resources: {len(rep['leaks'])}")
    for row in rep["leaks"][:20]:
        print(f"  [{row.get('kind')}] key={row.get('key')} "
              f"age={row.get('age_s')}s pid={row.get('pid')}")
        print(f"      born at {row.get('site')}")
    if len(rep["leaks"]) > 20:
        print(f"  ... and {len(rep['leaks']) - 20} more")
    anoms = rep["anomalies"]
    print(f"\nexactly-once anomalies (double discharge): {len(anoms)}")
    for a in anoms[:10]:
        print(f"  [{a.get('kind')}] key={a.get('key')} "
              f"pid={a.get('pid')} thread={a.get('thread')}")
    return 1 if bad else 0


def cmd_xlasan(args) -> int:
    """Merged XLA recompile/host-sync ledger (devtools/xlasan.py).
    Run the workload with RAY_TPU_XLASAN=1 first; every process drops
    a <pid>.json ledger into the xlasan dir at exit.  Exit 1 when any
    jit site recompiled past the budget (--budget overrides
    RAY_TPU_XLASAN_BUDGET), 0 on a clean run."""
    from ray_tpu.devtools import xlasan
    rep = xlasan.merged_report(args.dir)
    budget = args.budget if args.budget is not None \
        else rep.get("budget", xlasan.DEFAULT_BUDGET)
    storms = sorted(s for s, m in rep["sites"].items()
                    if m["recompiles"] > budget)
    rep["budget"], rep["storms"] = budget, storms
    if args.json:
        print(json.dumps(rep, indent=1, default=str))
        return 1 if storms else 0
    print(f"xlasan report ({rep['processes']} process(es), "
          f"{rep['compiles']} compile(s) / {rep['recompiles']} "
          f"recompile(s), budget {budget}, dir "
          f"{args.dir or xlasan.report_dir()})")
    if not rep["processes"]:
        print("no ledgers found — run the workload with "
              "RAY_TPU_XLASAN=1")
        return 0
    ordered = sorted(rep["sites"].items(),
                     key=lambda kv: (-kv[1]["recompiles"],
                                     -kv[1]["seconds"]))
    print("\njit sites (calls / compiles / recompiles / compile-s):")
    for site, m in ordered[:20]:
        mark = "  STORM" if site in storms else ""
        print(f"  {m['calls']:>7} {m['compiles']:>5} "
              f"{m['recompiles']:>5} {m['seconds']:>9.3f}  "
              f"{m['label']} @ {site}{mark}")
        if site in storms:
            for d in m["deltas"][-3:]:
                print(f"      {d}")
    syncs = sorted(rep["syncs"].items(),
                   key=lambda kv: -kv[1]["count"])
    print(f"\nhost-sync sites: {len(syncs)}")
    for site, m in syncs[:10]:
        print(f"  x{m['count']:<7} {m['seconds']:>9.3f}s  "
              f"{m['kind']} @ {site}")
    if storms:
        print(f"\nRECOMPILE STORMS ({len(storms)} site(s) over "
              f"budget {budget}) — fix the static/arg churn above")
    return 1 if storms else 0


def cmd_drain(args) -> int:
    """Gracefully drain one node (reference: `ray drain-node`): the
    GCS flips it alive -> draining and the node hands back queued
    work, migrates its actors, re-replicates sole object copies, then
    exits — a planned departure instead of a failure.  `node_id` is a
    hex prefix (from `ray_tpu status` / `ray_tpu list nodes`)."""
    addr = _head_address(args)
    if not addr:
        print("no cluster on record; pass --address H:P",
              file=sys.stderr)
        return 1
    from ray_tpu._private.gcs_service import GcsClient
    host, port = _parse_addr(addr)
    gcs = GcsClient(host, port)
    try:
        matches = [n for n in gcs.nodes()
                   if n["node_id"].hex().startswith(args.node_id)
                   and n.get("state") == "alive"]
        if not matches:
            print(f"no alive node matches {args.node_id!r}",
                  file=sys.stderr)
            return 1
        if len(matches) > 1:
            print(f"node id prefix {args.node_id!r} is ambiguous "
                  f"({len(matches)} matches)", file=sys.stderr)
            return 1
        nid = matches[0]["node_id"]
        ok = gcs.drain_node(nid, grace_s=args.grace,
                            reason="operator drain (CLI)")
    finally:
        gcs.close()
    if not ok:
        print("drain refused (node no longer alive?)", file=sys.stderr)
        return 1
    print(f"draining node {nid.hex()[:12]} (grace {args.grace:g}s)")
    return 0


def cmd_gcs(args) -> int:
    """Control-plane fault-tolerance card (reference: the HA-GCS face
    of `ray status`): recovery epoch, uptime, WAL size + ops since the
    last snapshot/compaction, last-snapshot age, and node membership
    counts including stale (recovered-but-not-yet-resynced) records."""
    addr = _head_address(args)
    if not addr:
        print("no cluster on record; pass --address H:P",
              file=sys.stderr)
        return 1
    from ray_tpu._private.gcs_service import GcsClient
    host, port = _parse_addr(addr)
    try:
        gcs = GcsClient(host, port)
    except OSError as e:
        print(f"GCS at {addr} unreachable: {e}", file=sys.stderr)
        return 1
    try:
        st = gcs.status()
    finally:
        gcs.close()
    if getattr(args, "json", False):
        print(json.dumps(st, indent=1, default=str))
        return 0
    print(f"GCS at {addr}")
    print(f"  epoch:         {st['epoch']}"
          + ("  (recovered from WAL/snapshot)" if st.get("recovered")
             else ""))
    print(f"  uptime:        {st['uptime_s']:.1f}s")
    print(f"  durable:       {'yes (WAL+snapshot)' if st['persistent'] else 'NO — head death loses the cluster'}")
    if st["persistent"]:
        print(f"  wal:           {_fmt_bytes(st['wal_bytes'])} "
              f"({st['wal_ops_since_snapshot']} ops since snapshot)")
        age = st.get("last_snapshot_age_s")
        print(f"  last snapshot: "
              f"{'never (no compaction yet)' if age is None else f'{age:.1f}s ago'}")
    counts = ", ".join(f"{k}={v}" for k, v in
                       sorted(st.get("nodes", {}).items())) or "none"
    print(f"  nodes:         {counts}"
          + (f"  ({st['stale_nodes']} stale, awaiting re-sync)"
             if st.get("stale_nodes") else ""))
    print(f"  named actors:  {st['named_actors']}  "
          f"actor directory: {st['actor_directory']}")
    print(f"  objects:       {st['objects_tracked']} tracked, "
          f"{st['small_objects']} inline/error payloads")
    return 0


def cmd_chaos(args) -> int:
    """Print/validate a chaos fault-injection spec (the schedule from
    --spec, or the ambient RAY_TPU_CHAOS_SPEC / config + legacy env
    specs).  Exit 0 on a valid schedule, 2 on a grammar error."""
    from ray_tpu._private.chaos import (FAULT_KINDS, chaos, parse_spec)
    from ray_tpu._private.config import config
    if args.spec is not None:
        try:
            entries = [s.to_dict() for s in parse_spec(args.spec)]
        except ValueError as e:
            print(f"invalid chaos spec: {e}", file=sys.stderr)
            return 2
        seed = config.chaos_seed
    else:
        entries = chaos.describe()
        seed = config.chaos_seed
    if args.json:
        print(json.dumps({"seed": seed, "entries": entries}, indent=1))
        return 0
    print(f"chaos seed: {seed} "
          f"(same seed + workload => identical fault trace)")
    if not entries:
        print("no faults armed (set RAY_TPU_CHAOS_SPEC or pass --spec)")
    else:
        cols = ["site", "kind", "p", "n"]
        if any(e.get("interval_s") for e in entries):
            cols.append("interval_s")   # storm spacing (preempt storms)
        _print_table(entries, cols)
    print(f"fault kinds: {', '.join(FAULT_KINDS)}")
    return 0


# ---------------------------------------------------------------------------
# doctor / top / bench-diff (control-plane observability)
# ---------------------------------------------------------------------------
def _render_doctor(rep: dict) -> str:
    """Text face of `ray_tpu doctor` (pure: unit-testable)."""
    lines = []
    findings = rep.get("findings") or []
    errors = [f for f in findings if f.get("severity") == "error"]
    warns = [f for f in findings if f.get("severity") != "error"]
    if not findings:
        lines.append("cluster is HEALTHY — no findings")
    elif errors:
        lines.append(f"cluster is UNHEALTHY — {len(errors)} error(s), "
                     f"{len(warns)} warning(s)")
    else:
        lines.append(f"cluster is healthy with {len(warns)} warning(s)")
    for f in findings:
        sev = (f.get("severity") or "?").upper()
        lines.append(f"  [{sev:<7}] {f.get('code')}: "
                     f"{f.get('summary')}")
        detail = f.get("detail") or {}
        for k in sorted(detail):
            v = detail[k]
            text = json.dumps(v, default=str)
            if len(text) > 160:
                text = text[:160] + "..."
            lines.append(f"             {k}: {text}")
    for pe in rep.get("probe_errors") or []:
        lines.append(f"  (probe {pe.get('probe')} failed: "
                     f"{pe.get('error')})")
    lines.append(f"probes run: {', '.join(rep.get('probes') or [])}")
    return "\n".join(lines)


def cmd_doctor(args) -> int:
    """Cluster health triage (state.doctor() via /api/doctor): fuses
    GCS liveness/WAL health, node reachability, stall + slow-RPC
    sentinel captures, object leak suspects, event-ring drops, lock
    inversions, serve shedding, and train goodput into prioritized
    findings with stable codes.  Exit 1 when any error-severity
    finding is present, 0 otherwise."""
    rep = _fetch_json(
        f"/api/doctor?gcs_stale_s={args.gcs_stale_s:g}"
        f"&leak_min_age_s={args.leak_min_age_s:g}", args)
    if args.json:
        print(json.dumps(rep, indent=1, default=str))
    else:
        print(_render_doctor(rep))
    return int(rep.get("exit_code") or 0)


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(vals: List[float], width: int = 32) -> str:
    vals = list(vals)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_CHARS[0] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK_CHARS[int((v - lo) / span * (len(_SPARK_CHARS) - 1))]
        for v in vals)


# Runtime gauges `ray_tpu top` always shows (one row per node each).
_TOP_BUILTINS = (
    "ray_tpu_tasks_pending",
    "ray_tpu_tasks_total",
    "ray_tpu_workers",
    "ray_tpu_actors_alive",
    "ray_tpu_objects_local",
    "ray_tpu_object_store_bytes_used",
)


def _series_rate(samples: List[list]) -> float:
    """Events/s over a monotone count series' sampled window."""
    if len(samples) < 2:
        return 0.0
    (t0, v0), (t1, v1) = samples[0], samples[-1]
    if t1 <= t0:
        return 0.0
    return max(v1 - v0, 0.0) / (t1 - t0)


def _render_top(series: List[dict], width: int = 32) -> str:
    """Text face of `ray_tpu top` (pure: unit-testable): sparkline
    per builtin gauge per node, plus the busiest RPC methods by
    handled rate with live in-flight counts."""
    lines = []
    by_name: Dict[str, List[dict]] = {}
    for row in series:
        by_name.setdefault(row.get("name", ""), []).append(row)
    lines.append("runtime (per node):")
    for name in _TOP_BUILTINS:
        for row in sorted(by_name.get(name, ()),
                          key=lambda r: r.get("node_id") or ""):
            samples = row.get("samples") or []
            vals = [s[1] for s in samples]
            last = vals[-1] if vals else 0.0
            nid = (row.get("node_id") or "?")[:8]
            shown = (_fmt_bytes(last) if name.endswith("bytes_used")
                     else f"{last:g}")
            lines.append(f"  {name:<34} {nid:<8} {shown:>10}  "
                         f"{_sparkline(vals, width)}")
    rpc_rows = []
    for row in by_name.get("ray_tpu_rpc_server_seconds", ()):
        method = (row.get("tags") or {}).get("method", "?")
        rate = _series_rate(row.get("samples") or [])
        rpc_rows.append((rate, method, row))
    inflight = {}
    for row in by_name.get("ray_tpu_rpc_inflight", ()):
        method = (row.get("tags") or {}).get("method", "?")
        samples = row.get("samples") or []
        if samples:
            inflight[method] = inflight.get(method, 0.0) + \
                samples[-1][1]
    if rpc_rows:
        lines.append("busiest RPC handlers (by handled/s):")
        rpc_rows.sort(key=lambda r: -r[0])
        for rate, method, row in rpc_rows[:10]:
            vals = [s[1] for s in row.get("samples") or []]
            lines.append(
                f"  {method:<26} {rate:>8.1f}/s  inflight "
                f"{inflight.get(method, 0):g}  "
                f"{_sparkline(vals, width)}")
    if not series:
        lines.append("  (no history samples yet — the ring fills at "
                     "metrics_history_resolution_s cadence)")
    return "\n".join(lines)


def cmd_top(args) -> int:
    """Live terminal view over the metrics history rings
    (/api/metrics/history): runtime gauges + busiest RPC handlers,
    refreshed every --interval seconds.  --iterations N renders N
    frames then exits (0 = until Ctrl-C)."""
    frames = 0
    try:
        while True:
            data = _fetch_json("/api/metrics/history", args)
            frame = _render_top(data.get("series") or [],
                                width=args.width)
            if frames and not args.no_clear:
                print("\x1b[2J\x1b[H", end="")
            print(frame)
            unreachable = data.get("unreachable_nodes") or []
            if unreachable:
                print("WARNING: partial view — unreachable nodes: "
                      + ", ".join(n[:12] for n in unreachable))
            frames += 1
            if args.iterations and frames >= args.iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


# Direction markers for bench-diff: a dotted metric path matching a
# higher-better marker regresses when it DROPS; lower-better (latency-
# shaped) paths regress when they RISE.  Higher-better wins ties
# ("speedup_p50" is a speedup, not a latency).
_BENCH_HIGHER = ("per_s", "_mb_s", "mbps", "throughput", "speedup",
                 "goodput", "goodput_fraction", "mfu", "tokens_s",
                 "qps")
_BENCH_LOWER = ("_us", "_ms", "_ns", "p50", "p95", "p99", "latency",
                "seconds", "_s_", "overhead", "stall")


def _bench_direction(path: str) -> Optional[str]:
    low = path.lower()
    if any(m in low for m in _BENCH_HIGHER):
        return "higher"
    if any(m in low for m in _BENCH_LOWER):
        return "lower"
    return None


def _bench_flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_bench_flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix[:-1]] = float(obj)
    return out


def _bench_diff(fresh: dict, baseline: dict,
                tolerance: float = 0.10) -> List[dict]:
    """Compare two bench-capture dicts metric by metric (pure:
    unit-testable).  Returns one row per baseline metric: {path,
    base, new, delta_pct, direction, regressed}; metrics with no
    direction marker (counts, config echoes) are informational and
    never regress, as are metrics absent from the fresh capture
    (legs not re-run)."""
    fflat = _bench_flatten(fresh)
    bflat = _bench_flatten(baseline)
    rows = []
    for path in sorted(bflat):
        base = bflat[path]
        new = fflat.get(path)
        direction = _bench_direction(path)
        row = {"path": path, "base": base, "new": new,
               "direction": direction, "delta_pct": None,
               "regressed": False}
        if new is not None and base:
            row["delta_pct"] = round(100.0 * (new - base) / abs(base),
                                     2)
        if new is not None and direction == "higher":
            row["regressed"] = new < base * (1.0 - tolerance)
        elif new is not None and direction == "lower":
            row["regressed"] = new > base * (1.0 + tolerance)
        rows.append(row)
    return rows


def cmd_bench_diff(args) -> int:
    """Regression gate over bench captures: compare a fresh
    BENCH_*/MICROBENCH_*/SERVE_BENCH_* JSON against a last-good one,
    direction-aware per metric (throughput-shaped metrics must not
    drop, latency-shaped must not rise, beyond --tolerance).  Exit 1
    on any regression, 0 otherwise."""
    with open(args.fresh) as f:
        fresh = json.load(f)
    with open(args.baseline) as f:
        baseline = json.load(f)
    rows = _bench_diff(fresh, baseline, tolerance=args.tolerance)
    regressions = [r for r in rows if r["regressed"]]
    if args.json:
        print(json.dumps({"rows": rows,
                          "regressions": len(regressions),
                          "tolerance": args.tolerance},
                         indent=1))
        return 1 if regressions else 0
    shown = [r for r in rows
             if r["regressed"] or (
                 r["direction"] and r["delta_pct"] is not None
                 and abs(r["delta_pct"]) >= 1.0)]
    print(f"bench-diff {args.fresh} vs {args.baseline} "
          f"(tolerance {args.tolerance:.0%}): "
          f"{len(rows)} metrics, {len(regressions)} regression(s)")
    table = [{
        "metric": r["path"],
        "base": f"{r['base']:g}",
        "new": "missing" if r["new"] is None else f"{r['new']:g}",
        "delta": ("" if r["delta_pct"] is None
                  else f"{r['delta_pct']:+.1f}%"),
        "want": r["direction"] or "-",
        "verdict": "REGRESSED" if r["regressed"] else "ok",
    } for r in shown]
    if table:
        _print_table(table, ["metric", "base", "new", "delta",
                             "want", "verdict"])
    else:
        print("(no directional metric moved by 1% or more)")
    return 1 if regressions else 0


# ---------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    raw = sys.argv[1:] if argv is None else list(argv)
    ap = argparse.ArgumentParser(prog="ray_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start head or join a cluster")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None, help="H:P of existing GCS")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--object-store-memory", type=int, default=0)
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument("--persist-dir", default="",
                   help="durable GCS state dir (survives head restarts)")
    p.add_argument("--timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop CLI-started processes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="cluster nodes + resources")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list runtime entities")
    p.add_argument("kind", choices=["tasks", "actors", "workers",
                                    "objects", "nodes", "pgs"])
    p.add_argument("--dashboard-url", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary", help="state rollups")
    p.add_argument("--dashboard-url", default=None)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser(
        "memory", help="cluster memory accounting (by kind/owner/node)")
    p.add_argument("--dashboard-url", default=None)
    p.add_argument("--group-by", choices=["node", "owner"],
                   default="node", dest="group_by")
    p.add_argument("--leak-suspects", action="store_true",
                   dest="leak_suspects",
                   help="flag old objects whose owner is dead or "
                        "whose borrow count is zero")
    p.add_argument("--min-age-s", type=float, default=60.0,
                   dest="min_age_s",
                   help="minimum age before an object can be a leak "
                        "suspect")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("metrics", help="Prometheus metrics dump")
    p.add_argument("--dashboard-url", default=None)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("timeline",
                       help="chrome-trace export of the task timeline")
    p.add_argument("--dashboard-url", default=None)
    p.add_argument("--out", default=None,
                   help="write the trace JSON to this file")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("job", help="job submission")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--address", default=None)
    j.add_argument("--working-dir", default=None)
    j.add_argument("--wait", action="store_true")
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("job_id")
        j.add_argument("--address", default=None)
    j = jsub.add_parser("list")
    j.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser(
        "stack",
        help="dump live worker stack traces (cluster-wide; optional "
             "task targeting and flamegraph sampling)")
    p.add_argument("task_id", nargs="?", default=None,
                   help="task id hex prefix: dump only the worker(s) "
                        "executing that task")
    p.add_argument("--dashboard-url", default=None)
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("--flame", action="store_true",
                   help="sample stacks and emit flamegraph.pl folded "
                        "format instead of one-shot dumps")
    p.add_argument("--samples", type=int, default=40,
                   help="samples per worker in --flame mode")
    p.add_argument("--interval", type=float, default=0.02,
                   help="seconds between samples in --flame mode")
    p.add_argument("--out", default=None,
                   help="write --flame output to this file")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("train", help="training telemetry")
    tsub = p.add_subparsers(dest="train_cmd", required=True)
    tp = tsub.add_parser(
        "status",
        help="per-run step decomposition (data_wait/compile/step/"
             "checkpoint/sync), live MFU, goodput ledger, and "
             "straggler verdicts")
    tp.add_argument("--dashboard-url", default=None)
    tp.add_argument("--run", default=None,
                    help="narrow to one run (default: all runs)")
    tp.add_argument("--json", action="store_true",
                    help="machine-readable dump")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("serve", help="declarative serve config")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    sp = ssub.add_parser("deploy", help="apply a YAML app config")
    sp.add_argument("config")
    sp.add_argument("--address", default=None,
                    help="cluster client address host:port")
    sp2 = ssub.add_parser(
        "status", help="deployments: replicas by state, queue depth, "
                       "shed counters, autoscale decision")
    sp2.add_argument("--address", default=None)
    sp2.add_argument("--json", action="store_true",
                     help="machine-readable dump")
    sp3 = ssub.add_parser("shutdown")
    sp3.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("microbench", help="core perf harness")
    p.set_defaults(fn=cmd_microbench)

    p = sub.add_parser(
        "drain", help="gracefully drain a node (planned departure)")
    p.add_argument("node_id", help="node id hex prefix")
    p.add_argument("--grace", type=float, default=30.0,
                   help="seconds the node gets to hand off its work")
    p.add_argument("--address", default=None, help="GCS address H:P")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser(
        "gcs", help="control-plane status: epoch / uptime / WAL / "
                    "last snapshot")
    p.add_argument("--address", default=None, help="GCS address H:P")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_gcs)

    p = sub.add_parser(
        "chaos", help="print/validate a chaos fault-injection spec")
    p.add_argument("--spec", default=None,
                   help="spec to validate (default: the ambient "
                        "config/env schedule)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "doctor",
        help="cluster health triage: prioritized findings with "
             "stable codes (exit 1 on error-severity findings)")
    p.add_argument("--dashboard-url", default=None)
    p.add_argument("--gcs-stale-s", type=float, default=15.0,
                   dest="gcs_stale_s",
                   help="flag GCS_UNREACHABLE when a node's last "
                        "successful GCS heartbeat is older than this")
    p.add_argument("--leak-min-age-s", type=float, default=60.0,
                   dest="leak_min_age_s",
                   help="minimum object age before it can be a "
                        "LEAK_SUSPECT")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "top",
        help="live terminal view over the metrics history rings "
             "(runtime gauges + busiest RPC handlers)")
    p.add_argument("--dashboard-url", default=None)
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between frames")
    p.add_argument("--iterations", type=int, default=0,
                   help="render N frames then exit (0 = until Ctrl-C)")
    p.add_argument("--width", type=int, default=32,
                   help="sparkline width in samples")
    p.add_argument("--no-clear", action="store_true", dest="no_clear",
                   help="append frames instead of clearing the screen")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "bench-diff",
        help="compare a fresh bench capture against a last-good one "
             "(direction-aware; exit 1 on regression)")
    p.add_argument("fresh", help="fresh capture JSON "
                                 "(BENCH_*/MICROBENCH_*/SERVE_BENCH_*)")
    p.add_argument("baseline", help="last-good capture JSON")
    p.add_argument("--tolerance", type=float, default=0.10,
                   help="allowed fractional change before a "
                        "directional metric counts as regressed")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_bench_diff)

    p = sub.add_parser(
        "locksan",
        help="merged lock-sanitizer report (inversions / long holds "
             "/ contention) from a RAY_TPU_LOCKSAN=1 run")
    p.add_argument("--dir", default=None,
                   help="report directory (default: the ambient "
                        "locksan dir)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_locksan)

    p = sub.add_parser(
        "leaksan",
        help="merged resource-leak ledger (leaked blocks/slots/fds/"
             "threads/series) from a RAY_TPU_LEAKSAN=1 run")
    p.add_argument("--dir", default=None,
                   help="ledger directory (default: the ambient "
                        "leaksan dir)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_leaksan)

    p = sub.add_parser(
        "xlasan",
        help="merged XLA recompile/host-sync ledger (per-jit-site "
             "compile counts, arg-shape deltas, storm verdicts) from "
             "a RAY_TPU_XLASAN=1 run")
    p.add_argument("--dir", default=None,
                   help="ledger directory (default: the ambient "
                        "xlasan dir)")
    p.add_argument("--budget", type=int, default=None,
                   help="recompiles allowed per jit site before it "
                        "counts as a storm (default: "
                        "RAY_TPU_XLASAN_BUDGET or 2)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_xlasan)

    # The rule-table epilog imports + registers the whole lint rule
    # set; only `ray_tpu lint -h` ever renders a subparser epilog, so
    # build it only on the lint path — every other command stays lean.
    epilog = None
    if raw and raw[0] == "lint":
        from ray_tpu.devtools.lint import cli as lint_cli
        epilog = lint_cli.rule_table_text()
    from ray_tpu.devtools.lint.cli import add_arguments
    p = sub.add_parser(
        "lint", help="static analysis for remote/actor/sharding code",
        epilog=epilog,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    add_arguments(p)
    p.set_defaults(fn=cmd_lint)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
