"""Head-node daemon: GCS server + head node service + dashboard.

Spawned detached by `python -m ray_tpu start --head` (reference analog:
`ray start --head` bringing up gcs_server + raylet + dashboard;
python/ray/scripts/scripts.py + node.py start_head_processes).

Prints one line `HEAD_READY=<json>` once serving, then runs until
SIGTERM/SIGINT."""

from __future__ import annotations

import argparse
import json
import os
import signal
import threading


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="GCS port (0 = pick a free one)")
    ap.add_argument("--num-cpus", type=float, default=None)
    ap.add_argument("--num-tpus", type=float, default=None)
    ap.add_argument("--resources", default="{}")
    ap.add_argument("--object-store-memory", type=int, default=0)
    ap.add_argument("--dashboard-port", type=int, default=8265)
    ap.add_argument("--persist-dir", default="",
                    help="durable GCS state dir (WAL); empty = in-memory")
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu._private.gcs_service import GcsServer
    from ray_tpu import dashboard

    gcs = GcsServer(host=args.host, port=args.port,
                    persist_dir=args.persist_dir or None)
    gcs.start()

    ray_tpu.init(
        num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources={k: float(v)
                   for k, v in json.loads(args.resources).items()},
        object_store_memory=args.object_store_memory or None,
        gcs_address=(args.host, gcs.port))

    dash_url = None
    if args.dashboard_port >= 0:
        try:
            httpd = dashboard.serve(port=args.dashboard_port,
                                    host=args.host)
            dash_url = f"http://{args.host}:{httpd.server_address[1]}"
        except OSError as e:
            print(f"dashboard disabled: {e}", flush=True)

    node = ray_tpu._session.node_service
    info = {
        "pid": os.getpid(),
        "gcs_address": f"{args.host}:{gcs.port}",
        "client_address": f"{args.host}:{node.control_port}",
        "dashboard_url": dash_url,
        "session_dir": ray_tpu._session.session_dir,
    }
    print(f"HEAD_READY={json.dumps(info)}", flush=True)
    # The launcher closes its end of our stdout pipe once it has the
    # READY line; route later prints to stderr (the daemon log file)
    # instead of dying on SIGPIPE.
    import sys
    sys.stdout = sys.stderr

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    while not stop.is_set():
        stop.wait(0.5)
    ray_tpu.shutdown()
    gcs.shutdown()


if __name__ == "__main__":
    main()
