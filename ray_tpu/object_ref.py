"""ObjectRef: a first-class future/handle to an object in the store.

Analog of the reference's `ObjectRef` (python/ray/_raylet.pyx / includes
object_ref.pxi).  Lifetime protocol (see _private/client.py for the
counting rules): a ref constructed as `owned` carries the entry's initial
refcount; a ref reconstructed from the wire announces itself with add_ref
on construction and remove_ref on GC.
"""

from __future__ import annotations

from typing import Any, Optional


class ObjectRef:
    __slots__ = ("_id", "_owned", "_released", "__weakref__")

    def __init__(self, id_bytes: bytes, owned: bool = True,
                 _announce: bool = True) -> None:
        self._id = id_bytes
        self._owned = owned
        self._released = False
        if not owned and _announce:
            client = _get_client()
            if client is not None:
                client.add_ref_async(id_bytes)

    # -- identity ----------------------------------------------------------
    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self) -> int:
        return hash(self._id)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    # -- future interface --------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu import get
        return get(self, timeout=timeout)

    def __reduce__(self):
        # Plain pickling (no client-mediated serialize) — e.g. a ref stored
        # in a config dict.  The counting hook lives in the client's
        # ref-aware serializer; this fallback just reconstructs a borrowed
        # ref in the target process.
        return (ObjectRef._from_wire, (self._id,))

    @staticmethod
    def _from_wire(id_bytes: bytes) -> "ObjectRef":
        return ObjectRef(id_bytes, owned=False)

    def _release(self) -> None:
        if self._released:
            return
        self._released = True
        client = _get_client()
        if client is not None:
            client.remove_ref_async(self._id)

    def __del__(self) -> None:
        try:
            self._release()
        except Exception:
            pass


def _get_client():
    from ray_tpu._private.client import get_global_client
    return get_global_client()
