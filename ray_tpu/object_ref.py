"""ObjectRef: a first-class future/handle to an object in the store.

Analog of the reference's `ObjectRef` (python/ray/_raylet.pyx / includes
object_ref.pxi).  Lifetime protocol (see _private/client.py for the
counting rules): a ref constructed as `owned` carries the entry's initial
refcount; a ref reconstructed from the wire announces itself with add_ref
on construction and remove_ref on GC.
"""

from __future__ import annotations

from typing import Any, Optional


class ObjectRef:
    __slots__ = ("_id", "_owned", "_released", "__weakref__")

    def __init__(self, id_bytes: bytes, owned: bool = True,
                 _announce: bool = True) -> None:
        self._id = id_bytes
        self._owned = owned
        self._released = False
        if not owned and _announce:
            client = _get_client()
            if client is not None:
                client.add_ref_async(id_bytes)

    # -- identity ----------------------------------------------------------
    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self) -> int:
        return hash(self._id)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    # -- future interface --------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu import get
        return get(self, timeout=timeout)

    def __reduce__(self):
        # Plain pickling (no client-mediated serialize) — e.g. a ref stored
        # in a config dict.  The counting hook lives in the client's
        # ref-aware serializer; this fallback just reconstructs a borrowed
        # ref in the target process.
        return (ObjectRef._from_wire, (self._id,))

    @staticmethod
    def _from_wire(id_bytes: bytes) -> "ObjectRef":
        return ObjectRef(id_bytes, owned=False)

    def _release(self) -> None:
        if self._released:
            return
        self._released = True
        client = _get_client()
        if client is not None:
            client.remove_ref_async(self._id)

    def __del__(self) -> None:
        try:
            self._release()
        except Exception:
            pass


def _get_client():
    from ray_tpu._private.client import get_global_client
    return get_global_client()


class ObjectRefGenerator:
    """Streaming-generator handle (reference: ObjectRefGenerator,
    _raylet.pyx streaming generators): iterating yields ObjectRefs to
    items AS THE TASK PRODUCES THEM — item 0 is consumable while the
    generator task is still running.  Exhaustion raises StopIteration;
    a mid-generator exception surfaces on the next consumed ref."""

    def __init__(self, completion_ref: "ObjectRef", client) -> None:
        self._completion_ref = completion_ref   # end/error signal
        self._stream_id = completion_ref.binary()
        self._client = client
        self._index = 0
        self._released = False

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> "ObjectRef":
        reply = self._client.stream_next(self._stream_id, self._index)
        if reply["status"] == "item":
            self._index += 1
            return ObjectRef(reply["object_id"], owned=False)
        # end of stream: the completion object carries None on success
        # or the task error — get() it so failures propagate.
        from ray_tpu import get
        get(self._completion_ref)
        raise StopIteration

    def completed(self) -> "ObjectRef":
        """Ref that resolves when the generator task finishes
        (reference: generator 'completed' sentinel)."""
        return self._completion_ref

    def __del__(self) -> None:
        if self._released:
            return
        self._released = True
        try:
            self._client.stream_release(self._stream_id)
        except Exception:
            pass
