"""Process-level collectives: the `ray.util.collective` analog.

Reference surface: python/ray/util/collective/collective.py:258-594
(init_collective_group, declare_collective_group, allreduce, barrier,
reducescatter, allgather, broadcast, send, recv, get_rank,
get_collective_group_size, destroy_collective_group).

TPU-first split of responsibilities:

* INSIDE a compiled program, collectives are XLA's job — `psum` /
  `all_gather` / `ppermute` over `jax.sharding.Mesh` axes ride the ICI
  and fuse with compute.  Nothing here is for that path.
* BETWEEN processes (actors coordinating outside jit — parameter
  exchange in Tune/PBT, rollout aggregation, eval fan-in), the
  reference stands up NCCL/gloo rings.  Here the transport IS the
  runtime's native object plane: each rank `put`s its shard into the
  zero-copy shm store and peers `get` it (cross-node gets ride the
  object-transfer plane), with GCS KV as the rendezvous/sequencing
  board.  No second networking stack to configure, and payloads move
  through the same spill/transfer machinery as everything else.

Semantics notes vs the reference:
* Arrays (numpy or jax) are reduced with f-order-preserving numpy ops;
  numpy inputs are ALSO updated in place (reference mutates tensors in
  place); the reduced array is always returned.
* Every rank must call the same collectives in the same order (standard
  collective contract) — a per-group operation counter sequences keys.
* Garbage: each rank remembers exactly which keys it published per op.
  Completing a *synchronizing* op at seq S (one whose completion proves
  every rank has entered S: barrier, allreduce, allgather,
  reducescatter) makes every key with seq < S dead, so they are deleted
  at the next op.  Broadcast does NOT synchronize (the src publishes
  and returns without waiting), so it never advances the horizon — its
  keys are reaped by the next synchronizing op or at destroy.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu._private.client import get_global_client

_NS = "collective"
_POLL_S = 0.002
# Finite default so a protocol bug (mismatched op order, dead peer)
# fails loudly instead of deadlocking the caller forever.
_DEFAULT_TIMEOUT_S = 300.0

_lock = threading.RLock()
_groups: Dict[str, "_Group"] = {}


class _Group:
    def __init__(self, name: str, world_size: int, rank: int) -> None:
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.seq = 0           # collective op counter
        self.p2p_seq: Dict[tuple, int] = {}   # (src, dst) -> counter
        self._refs: List[tuple] = []          # (seq, ObjectRef) pins
        # p2p pins live on their own ledger: p2p sequencing is per-pair
        # and independent of the collective counter, so the seq-horizon
        # GC must not touch them.  Released on receiver ack or destroy.
        self._p2p_refs: Dict[tuple, Any] = {}   # (dst, seq) -> ObjectRef
        # GC bookkeeping: exact keys this rank published per op, the
        # proven-safe horizon (all ranks have finished every op < this),
        # and how far deletion has already run.
        self._published: Dict[int, List[bytes]] = {}   # seq -> kv keys
        self._safe_below = 0
        self._gc_done_below = 0


def _client():
    c = get_global_client()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized in this process")
    return c


def _key(group: str, seq: int, tag: str) -> bytes:
    return f"{group}/{seq:09d}/{tag}".encode()


def _put_blob(group: _Group, seq: int, tag: str, value: Any,
              p2p_dst: Optional[int] = None) -> None:
    """Publish a value on the op board.  Small values inline into KV;
    big arrays go through the object store and only the ref id lands in
    KV (zero-copy within a node, transfer plane across nodes)."""
    blob = pickle.dumps(value, protocol=5)
    if len(blob) > 64 * 1024:
        ref = ray_tpu.put(value)
        if p2p_dst is not None:
            group._p2p_refs[(p2p_dst, seq)] = ref
        else:
            group._refs.append((seq, ref))    # pin until GC horizon
        payload = b"R" + ref.binary()
    else:
        payload = b"I" + blob
    key = _key(group.name, seq, tag)
    if p2p_dst is None:
        group._published.setdefault(seq, []).append(key)
    _client().kv_put(_NS, key, payload)


def _get_blob(group: _Group, seq: int, tag: str,
              timeout: Optional[float] = _DEFAULT_TIMEOUT_S) -> Any:
    """Blocking read via the node's parked kv_wait (long-poll): no
    2ms client polling, no latency floor — the value arrives on the
    same push that stores it."""
    key = _key(group.name, seq, tag)
    deadline = None if timeout is None else time.monotonic() + timeout
    c = _client()
    while True:
        step = 30.0
        if deadline is not None:
            step = min(step, deadline - time.monotonic())
            if step <= 0:
                raise TimeoutError(
                    f"collective {tag} (group={group.name!r} seq={seq}) "
                    f"timed out after {timeout}s")
        raw = c.kv_wait(_NS, key, max(step, 0.001))
        if raw is not None:
            break
    if raw[:1] == b"R":
        from ray_tpu.object_ref import ObjectRef
        return ray_tpu.get(ObjectRef._from_wire(raw[1:]))
    return pickle.loads(raw[1:])


def _gc(group: _Group) -> None:
    """Delete this rank's published keys for every op that is provably
    finished on all ranks (seq < _safe_below).  Exact-key deletion —
    no prefix matching, so rank 1 can never clobber rank 12's data."""
    if group._gc_done_below >= group._safe_below:
        return
    c = _client()
    for s in range(group._gc_done_below, group._safe_below):
        for key in group._published.pop(s, ()):
            c.kv_del(_NS, key)
    group._gc_done_below = group._safe_below
    group._refs = [(s, r) for (s, r) in group._refs
                   if s >= group._safe_below]


def _mark_synced(group: _Group, seq: int) -> None:
    """Record that the op at `seq` synchronized all ranks: its
    completion proves every rank entered op `seq`, so every op < seq is
    finished everywhere and its keys are dead."""
    group._safe_below = max(group._safe_below, seq)


# ---------------------------------------------------------------------------
# group management
# ---------------------------------------------------------------------------
def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join `group_name` as `rank` of `world_size`.  Called inside each
    participating actor/task (reference: collective.py:258)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside [0, {world_size})")
    with _lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized "
                               f"in this process")
        _groups[group_name] = _Group(group_name, world_size, rank)
    # Rendezvous: every rank registers, all wait for a full roster.
    _client().kv_put(_NS, f"{group_name}/roster/{rank}".encode(),
                     str(world_size).encode())
    g = _groups[group_name]
    deadline = time.monotonic() + 120.0
    while True:
        n = len(_client().kv_keys(_NS, f"{group_name}/roster/".encode()))
        if n >= world_size:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"collective group {group_name!r}: only {n}/{world_size} "
                f"ranks joined within 120s")
        time.sleep(_POLL_S)


def declare_collective_group(actors, world_size: Optional[int] = None,
                             ranks: Optional[List[int]] = None,
                             group_name: str = "default") -> None:
    """Declare a group FROM THE DRIVER for a set of actors (reference:
    collective.py declare_collective_group): each actor's first
    collective op auto-joins with the rank declared for its actor id —
    no explicit init_collective_group call inside the actors."""
    world = world_size if world_size is not None else len(actors)
    rank_list = ranks if ranks is not None else list(range(len(actors)))
    if sorted(rank_list) != list(range(world)):
        raise ValueError(f"ranks {rank_list} must cover 0..{world - 1}")
    c = _client()
    for actor, rank in zip(actors, rank_list):
        c.kv_put(_NS, f"{group_name}/declared/"
                      f"{actor._actor_id.hex()}".encode(),
                 f"{rank}/{world}".encode())


def _maybe_auto_init(name: str) -> Optional[_Group]:
    """Join a driver-declared group using this actor's identity."""
    import ray_tpu
    ctx = ray_tpu.get_runtime_context()
    aid = ctx.get_actor_id()
    if aid is None:
        return None
    raw = _client().kv_get(_NS, f"{name}/declared/{aid}".encode())
    if raw is None:
        return None
    rank_s, _, world_s = raw.decode().partition("/")
    init_collective_group(int(world_s), int(rank_s), group_name=name)
    with _lock:
        return _groups.get(name)


def is_group_initialized(group_name: str = "default") -> bool:
    with _lock:
        return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def destroy_collective_group(group_name: str = "default") -> None:
    """Leave the group.  Deletes only THIS rank's keys — peers may not
    have read the last op's data yet — except the final leaver, who
    sweeps the whole prefix once the roster is empty."""
    with _lock:
        g = _groups.pop(group_name, None)
    if g is None:
        return
    c = _client()
    c.kv_del(_NS, f"{group_name}/roster/{g.rank}".encode())
    # Exact-key deletion from the published ledger (covers broadcast
    # "result" keys from any src rank, never touches peers' keys).
    for keys in g._published.values():
        for key in keys:
            c.kv_del(_NS, key)
    g._published.clear()
    prefix = f"{group_name}/".encode()
    for key in c.kv_keys(_NS, prefix):
        # p2p keys aren't in the ledger; parse the tag exactly —
        # substring matching would let rank 1 delete rank 12's data.
        parts = key[len(prefix):].split(b"/", 1)
        if len(parts) != 2:
            continue
        tag = parts[1].decode(errors="replace")
        if (tag.startswith(f"p2p/{g.rank}->")
                or tag.startswith(f"p2pack/{g.rank}->")):
            c.kv_del(_NS, key)
    if not c.kv_keys(_NS, f"{group_name}/roster/".encode()):
        for key in c.kv_keys(_NS, prefix):
            c.kv_del(_NS, key)


def _group(name: str) -> _Group:
    with _lock:
        g = _groups.get(name)
    if g is None:
        g = _maybe_auto_init(name)
    if g is None:
        raise RuntimeError(
            f"collective group {name!r} is not initialized in this "
            f"process (call init_collective_group, or declare it from "
            f"the driver with declare_collective_group)")
    return g


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------
_REDUCERS = {
    "sum": lambda stack: np.sum(stack, axis=0),
    "prod": lambda stack: np.prod(stack, axis=0),
    "max": lambda stack: np.max(stack, axis=0),
    "min": lambda stack: np.min(stack, axis=0),
    "mean": lambda stack: np.mean(stack, axis=0),
}


def _finish(arr, out):
    """In-place update for numpy inputs + always return the result."""
    if isinstance(arr, np.ndarray):
        arr[...] = out
        return arr
    try:
        import jax.numpy as jnp
        return jnp.asarray(out)
    except ImportError:           # pragma: no cover
        return out


def allreduce(arr, op: str = "sum", group_name: str = "default"):
    """Reduce across ranks (rank-0 root reduce + broadcast over the
    object plane).  Reference: collective.py:327."""
    g = _group(group_name)
    seq = g.seq
    g.seq += 1
    _gc(g)
    reducer = _REDUCERS.get(op)
    if reducer is None:
        raise ValueError(f"unknown reduce op {op!r} "
                         f"(have {sorted(_REDUCERS)})")
    local = np.asarray(arr)
    if g.world_size == 1:
        _mark_synced(g, seq + 1)
        return _finish(arr, local)
    _put_blob(g, seq, f"r{g.rank}", local)
    if g.rank == 0:
        parts = [_get_blob(g, seq, f"r{r}") for r in range(g.world_size)]
        out = reducer(np.stack([np.asarray(p) for p in parts]))
        out = out.astype(local.dtype) if op != "mean" else out
        _put_blob(g, seq, "result", out)
    else:
        out = np.asarray(_get_blob(g, seq, "result"))
    # Root read every rank's input; non-roots read the root's result,
    # which implies the same — everyone has entered this op.
    _mark_synced(g, seq)
    return _finish(arr, out)


def barrier(group_name: str = "default") -> None:
    """All ranks wait until every rank arrives (collective.py:367)."""
    g = _group(group_name)
    seq = g.seq
    g.seq += 1
    _gc(g)
    if g.world_size == 1:
        _mark_synced(g, seq + 1)
        return
    _put_blob(g, seq, f"r{g.rank}", True)
    for r in range(g.world_size):
        _get_blob(g, seq, f"r{r}")
    _mark_synced(g, seq)


def broadcast(arr, src_rank: int = 0, group_name: str = "default"):
    """Copy src_rank's array to every rank (collective.py:389)."""
    g = _group(group_name)
    seq = g.seq
    g.seq += 1
    _gc(g)
    if g.world_size == 1:
        _mark_synced(g, seq + 1)
        return _finish(arr, np.asarray(arr))
    if g.rank == src_rank:
        _put_blob(g, seq, "result", np.asarray(arr))
        out = np.asarray(arr)
    else:
        out = np.asarray(_get_blob(g, seq, "result"))
    # NOT synced: the src published and moved on without waiting, and a
    # non-src rank only proved the src entered this op — a slow peer may
    # still be reading earlier keys, so the horizon must not advance.
    return _finish(arr, out)


def allgather(arr, group_name: str = "default") -> List[np.ndarray]:
    """Every rank receives [arr_0, ..., arr_{n-1}] (collective.py:433)."""
    g = _group(group_name)
    seq = g.seq
    g.seq += 1
    _gc(g)
    local = np.asarray(arr)
    if g.world_size == 1:
        _mark_synced(g, seq + 1)
        return [local]
    _put_blob(g, seq, f"r{g.rank}", local)
    out = [np.asarray(_get_blob(g, seq, f"r{r}"))
           for r in range(g.world_size)]
    _mark_synced(g, seq)
    return out


def reducescatter(arr, op: str = "sum",
                  group_name: str = "default") -> np.ndarray:
    """Reduce then scatter row-shards: rank i gets the i-th 1/n slice
    along axis 0 of the reduction (collective.py:469)."""
    g = _group(group_name)
    reducer = _REDUCERS.get(op)
    if reducer is None:
        raise ValueError(f"unknown reduce op {op!r}")
    local = np.asarray(arr)
    if local.shape[0] % g.world_size:
        raise ValueError(
            f"reducescatter needs dim0 ({local.shape[0]}) divisible by "
            f"world_size ({g.world_size})")
    seq = g.seq
    g.seq += 1
    _gc(g)
    if g.world_size == 1:
        _mark_synced(g, seq + 1)
        return reducer(np.stack([local]))
    # Scatter-then-reduce: each rank publishes only the slice destined
    # for each peer, so no rank ever holds the full stacked array.
    shards = np.split(local, g.world_size, axis=0)
    for r, shard in enumerate(shards):
        if r != g.rank:
            _put_blob(g, seq, f"r{g.rank}:{r}", shard)
    parts = [shards[g.rank] if r == g.rank
             else np.asarray(_get_blob(g, seq, f"r{r}:{g.rank}"))
             for r in range(g.world_size)]
    out = reducer(np.stack(parts))
    _mark_synced(g, seq)
    return out if op == "mean" else out.astype(local.dtype)


def send(arr, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (collective.py:551).  Pairwise FIFO.
    Large-payload pins are released when the receiver acks (or at
    destroy_collective_group)."""
    g = _group(group_name)
    if dst_rank == g.rank:
        raise ValueError("send to self")
    pair = (g.rank, dst_rank)
    seq = g.p2p_seq.get(pair, 0)
    g.p2p_seq[pair] = seq + 1
    # Release pins the receiver has acked.
    c = _client()
    for (dst, s) in list(g._p2p_refs):
        if dst != dst_rank:
            continue
        ack = _key(g.name, s, f"p2pack/{g.rank}->{dst}")
        if c.kv_get(_NS, ack) is not None:
            del g._p2p_refs[(dst, s)]
            c.kv_del(_NS, ack)
    _put_blob(g, seq, f"p2p/{g.rank}->{dst_rank}", np.asarray(arr),
              p2p_dst=dst_rank)


def recv(arr, src_rank: int, group_name: str = "default"):
    """Point-to-point receive matching `send` (collective.py:571)."""
    g = _group(group_name)
    if src_rank == g.rank:
        raise ValueError("recv from self")
    pair = (src_rank, g.rank)
    seq = g.p2p_seq.get(pair, 0)
    g.p2p_seq[pair] = seq + 1
    out = np.asarray(_get_blob(g, seq, f"p2p/{src_rank}->{g.rank}"))
    c = _client()
    c.kv_del(_NS, _key(g.name, seq, f"p2p/{src_rank}->{g.rank}"))
    # Ack so the sender can release its object-store pin.
    c.kv_put(_NS, _key(g.name, seq, f"p2pack/{src_rank}->{g.rank}"),
             b"1")
    return _finish(arr, out)
