"""State API: live introspection of the running cluster.

Reference surface: python/ray/util/state/api.py (list_actors :429,
list_tasks :576, list_objects :629, list_nodes :502, list_workers :523,
list_placement_groups :475, summarize_tasks :793).

Implementation: one `state_dump` RPC to the local node service, which
snapshots its own tables and — in multinode mode — fans out to every
alive peer over the control plane and merges.  Filters run driver-side
(the reference pushes predicates to the dashboard head; at our scale a
post-filter over the merged snapshot is the same observable behavior).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.client import get_global_client


def _client():
    client = get_global_client()
    if client is None:
        # Implicit init here would silently mask a misconfigured
        # session — raise the same error every other API uses.
        raise RuntimeError("ray_tpu is not initialized")
    return client


def _dump() -> dict:
    return _client().state_dump(cluster=True)


def _apply_filters(rows: List[dict],
                   filters: Optional[Sequence[Tuple[str, str, Any]]],
                   limit: int) -> List[dict]:
    """Filters are (key, "=" | "!=", value) triples, per the reference's
    list API predicate form."""
    out = []
    for row in rows:
        ok = True
        for key, pred, val in (filters or []):
            have = row.get(key)
            if pred == "=":
                ok = have == val
            elif pred == "!=":
                ok = have != val
            else:
                raise ValueError(f"unsupported predicate {pred!r} "
                                 "(use '=' or '!=')")
            if not ok:
                break
        if ok:
            out.append(row)
            if len(out) >= limit:
                break
    return out


def list_tasks(filters=None, limit: int = 10_000) -> List[dict]:
    return _apply_filters(_dump()["tasks"], filters, limit)


def list_actors(filters=None, limit: int = 10_000) -> List[dict]:
    return _apply_filters(_dump()["actors"], filters, limit)


def list_workers(filters=None, limit: int = 10_000) -> List[dict]:
    return _apply_filters(_dump()["workers"], filters, limit)


def list_objects(filters=None, limit: int = 10_000) -> List[dict]:
    return _apply_filters(_dump()["objects"], filters, limit)


def list_placement_groups(filters=None, limit: int = 10_000) -> List[dict]:
    return _apply_filters(_dump()["placement_groups"], filters, limit)


def list_nodes(filters=None, limit: int = 10_000) -> List[dict]:
    dump = _dump()
    nodes = dump.get("nodes")
    if nodes is None:   # single-node mode: synthesize the head entry
        nodes = [{"node_id": dump["node_id"], "state": "alive",
                  "pending_tasks": dump["pending_tasks"]}]
    rows = []
    for n in nodes:
        row = dict(n)
        nid = row.get("node_id")
        if isinstance(nid, bytes):
            row["node_id"] = nid.hex()
        rows.append(row)
    return _apply_filters(rows, filters, limit)


def _percentile(sorted_vals: List[float], q: float) -> float:
    # Kept as a name other modules import; the one implementation
    # lives in util.metrics next to its histogram sibling.
    from ray_tpu.util.metrics import percentile
    return percentile(sorted_vals, q)


def summarize_tasks() -> Dict[str, Dict[str, Any]]:
    """Task counts grouped by name then state (api.py:793), plus
    per-stage latency aggregates from the lifecycle trace ring.

    Each name maps to its live-state counts ({"pending": n, ...}), a
    "finished"/"failed" count from completed lifecycles, and a
    "stages" dict of {stage: {count, p50_s, p95_s, max_s}} over the
    submitted→queued→worker_assigned→executing→finished transitions —
    the queue-wait / scheduling-delay decomposition the reference
    exposes through `ray summary tasks`.

    Completed counts and stage percentiles come from the bounded
    per-node event ring (profile_events_max, default 10k entries
    shared with all spans): they are a recent-window sample, not an
    all-time total — long-running workloads will see old completions
    evicted."""
    from ray_tpu._private.tracing import stage_durations

    out: Dict[str, Dict[str, Any]] = {}
    for t in _dump()["tasks"]:
        per = out.setdefault(t["name"] or "<anonymous>", {})
        per[t["state"]] = per.get(t["state"], 0) + 1
    # Completed tasks left the live tables; their lifecycle records
    # (stage checkpoint dicts) live in the per-node event ring.
    samples: Dict[str, Dict[str, List[float]]] = {}
    for ev in _client().timeline_events(cluster=True):
        if ev.get("kind") == "drain":
            # Graceful node drains surface alongside the task rollup
            # (reason, grace, and what moved where) — a drained node's
            # zero-failure departure should be visible, not silent.
            per = out.setdefault("node:drain", {})
            per["drains"] = per.get("drains", 0) + 1
            per.setdefault("events", []).append({
                k: ev.get(k) for k in
                ("node_id", "reason", "grace_s", "tasks_handed_back",
                 "actors_migrated", "objects_moved", "completed")})
            continue
        if ev.get("kind") == "gcs_restart":
            # Control-plane restarts a node rode out (reconnect +
            # re-sync): a survived kill -9 of the GCS should be
            # visible in the rollup, not silent.
            per = out.setdefault("node:gcs_restart", {})
            per["restarts"] = per.get("restarts", 0) + 1
            per.setdefault("events", []).append({
                k: ev.get(k) for k in
                ("node_id", "epoch", "resync_s",
                 "objects_republished", "actors_republished")})
            continue
        if ev.get("kind") == "stall":
            # Stall-sentinel captures: count + the captured stacks, so
            # "why has this been executing for ten minutes" is
            # answerable from the summary alone.
            per = out.setdefault(ev.get("task_name") or "<anonymous>",
                                 {})
            per["stalls"] = per.get("stalls", 0) + 1
            per.setdefault("stall_events", []).append({
                k: ev.get(k) for k in
                ("task_id", "elapsed_s", "threshold_s", "node_id",
                 "pid", "stack")})
            continue
        if ev.get("kind") != "lifecycle":
            continue
        name = ev.get("task_name") or "<anonymous>"
        per = out.setdefault(name, {})
        state = "failed" if ev.get("failed") else "finished"
        per[state] = per.get(state, 0) + 1
        by_stage = samples.setdefault(name, {})
        for stage, dur in stage_durations(ev.get("stages") or {}).items():
            by_stage.setdefault(stage, []).append(dur)
    for name, by_stage in samples.items():
        stages = out[name].setdefault("stages", {})
        for stage, vals in by_stage.items():
            vals.sort()
            stages[stage] = {
                "count": len(vals),
                "p50_s": _percentile(vals, 0.50),
                "p95_s": _percentile(vals, 0.95),
                "max_s": vals[-1],
            }
    return out


def summarize_actors() -> Dict[str, Dict[str, int]]:
    out: Dict[str, Dict[str, int]] = {}
    for a in _dump()["actors"]:
        per = out.setdefault(a["class_name"] or "<anonymous>", {})
        per[a["state"]] = per.get(a["state"], 0) + 1
    return out


def summarize_objects() -> Dict[str, Any]:
    objs = _dump()["objects"]
    by_loc: Dict[str, int] = {}
    total = 0
    for o in objs:
        by_loc[str(o["loc"])] = by_loc.get(str(o["loc"]), 0) + 1
        total += o["size"] or 0
    return {"count": len(objs), "total_bytes": total, "by_loc": by_loc}


def locksan_report(directory: Optional[str] = None) -> Dict[str, Any]:
    """Merged concurrency-sanitizer report (devtools/locksan.py).

    Requires running the workload with ``RAY_TPU_LOCKSAN=1``: every
    process (driver, node services, workers — the env var inherits)
    instruments its locks and drops a ``<pid>.json`` report into the
    locksan dir; this merges them with the calling process's live
    state.  Keys: ``processes``, ``acquires``, ``edges`` (observed
    acquisition orders ``"A || B"`` -> count), ``contention`` (by
    lock creation site), ``inversions`` (lock-order cycles actually
    witnessed at runtime — each a deadlock under the right timing),
    and ``long_holds`` (locks held past ``lock_hold_warn_ms``, with
    the holder's stack).  Unlike the other state APIs this does not
    need an initialized runtime — reports outlive the cluster."""
    from ray_tpu.devtools import locksan
    return locksan.merged_report(directory)


def leaksan_report(directory: Optional[str] = None) -> Dict[str, Any]:
    """Merged resource-leak ledger (devtools/leaksan.py).

    Requires running the workload with ``RAY_TPU_LEAKSAN=1``: every
    process (driver, node services, workers — the env var inherits)
    tracks acquire/release of KV blocks, admission slots, spill fds,
    channel mmap files, service threads, and per-instance metric
    series, and drops a ``<pid>.json`` ledger into the leaksan dir at
    exit; this merges them with the calling process's live state.
    Keys: ``processes``, ``registrations``, ``registered`` /
    ``discharged`` (per-kind totals), ``leaks`` (resources still live
    when their process dumped — each with its creation site and age),
    ``leak_counts`` (per kind), and ``anomalies`` (a release that
    fired twice — the exactly-once contract cuts both ways).  Like
    locksan_report, this needs no initialized runtime — ledgers
    outlive the cluster."""
    from ray_tpu.devtools import leaksan
    return leaksan.merged_report(directory)


def xlasan_report(directory: Optional[str] = None) -> Dict[str, Any]:
    """Merged XLA recompile/host-sync ledger (devtools/xlasan.py).

    Requires running the workload with ``RAY_TPU_XLASAN=1``: every
    process (driver, workers — the env var inherits) wraps ``jax.jit``
    so each jit construction site accumulates compile count, wall
    seconds, and argument shape/dtype deltas, and wraps
    ``jax.block_until_ready``/``jax.device_get`` into a host-sync
    ledger; each process drops a ``<pid>.json`` into the xlasan dir at
    exit and this merges them with the calling process's live state.
    Keys: ``processes``, ``budget``, ``sites`` (construction site ->
    {label, calls, compiles, recompiles, seconds, deltas}), ``syncs``
    (call site -> {kind, count, seconds}), and ``storms`` — sites
    whose recompile count exceeds the budget
    (``RAY_TPU_XLASAN_BUDGET``, default 2).  Like the other sanitizer
    reports, this needs no initialized runtime."""
    from ray_tpu.devtools import xlasan
    return xlasan.merged_report(directory)


def train_summary(run: Optional[str] = None) -> Dict[str, Any]:
    """Training telemetry rollup (train/telemetry.py): per-run step
    decomposition, live MFU/goodput, and straggler verdicts.

    Every train worker's telemetry session publishes a snapshot
    (cumulative phase totals, goodput ledger, rolling step window,
    decayed tokens/s + MFU) to the control-plane KV about once a
    second; this merges them per run:

    * phases: {data_wait, compile, step, checkpoint, sync} seconds +
      fraction of attributed step time — the ingest-vs-compute
      decomposition;
    * verdict / bound: "input-bound: data_wait 41% of step time"
      when data_wait crosses ``train_input_bound_fraction``, else
      compile-bound / compute-bound — the measured target the
      ingest-disaggregation and sharded-update work optimizes
      against;
    * ledger: run wall-clock classified productive / compile /
      input_wait / checkpoint / sync / restart_recovery / idle —
      chaos kills, drains, and GCS outages show up as quantified
      lost goodput (restart_recovery persists across worker
      restarts);
    * coverage: ledger seconds over wall clock (≈1.0 when the loop
      is instrumented end to end);
    * tokens_per_s / mfu: decayed-window live rates (gang tokens/s
      summed, MFU averaged over reporting workers);
    * stragglers: per-rank step-phase p95 vs the gang median
      (flagged above ``train_straggler_multiple``), plus
      straggler_captures for ranks whose one-shot stack dump fired.

    With `run`, returns that run's rollup alone; otherwise
    ``{"runs": {name: rollup}}``.  The same data serves the
    dashboard's ``/api/train`` and ``ray_tpu train status``."""
    from ray_tpu.train import telemetry

    client = _client()
    metas = telemetry.read_run_metas(client)
    if run is not None:
        meta = metas.get(run)
        if meta is None:
            raise KeyError(f"unknown train run {run!r}; known: "
                           f"{sorted(metas)}")
        return telemetry.summarize_run(
            meta, telemetry.read_snapshots(client, run),
            telemetry.read_straggler_captures(client, run))
    return {"runs": {
        name: telemetry.summarize_run(
            meta, telemetry.read_snapshots(client, name),
            telemetry.read_straggler_captures(client, name))
        for name, meta in sorted(metas.items())}}


def memory_summary(leak_min_age_s: float = 60.0,
                   top_n: int = 200) -> Dict[str, Any]:
    """Cluster-wide object-store memory accounting (reference surface:
    `ray memory` / memory_summary in _private/state.py).

    Every node reports its object-directory breakdown — per-object
    size, owner (creating client), reference kind (owned / borrowed /
    pinned_by_actor / spilled / drain_replica), holder set, and age —
    and the head aggregates:

    * by_kind / by_owner: {count, bytes} rollups;
    * by_node: per-node {count, bytes, by_kind} next to the node's
      actual shm store {used_bytes, capacity_bytes} so directory
      accounting can be reconciled against real store usage;
    * leak_suspects: READY objects at least `leak_min_age_s` old whose
      owner client is dead (nothing will ever delete them) or whose
      borrowed replica's refcount dropped to zero;
    * objects: the `top_n` largest rows for drill-down;
    * kv_blocks: paged-KV serving block-pool occupancy
      {used, cached, free} summed over the ray_tpu_kv_blocks gauges
      (all engines' series) flushed to THIS node's metric aggregator
      (empty when no paged LLM engine is running; replicas on other
      nodes report to their own node's scrape) — HBM the serve
      engines hold OUTSIDE the object store.  Caveat: gauges are
      push-model, so a replica killed UNCLEANLY (SIGKILL/OOM — its
      engine never ran stop()'s series removal) leaves its last
      samples in the aggregate until the node restarts; nonzero
      kv_blocks with no running engine is that artifact, not a leak.

    The same data serves `/api/memory` on the dashboard and the
    `ray_tpu memory` CLI table."""
    dump = _dump()
    objs = dump.get("objects") or []
    live_clients = set(dump.get("clients") or [])
    stores = dict(dump.get("stores") or {})
    if not stores and dump.get("store"):
        stores = {dump.get("node_id", "node"): dump["store"]}
    by_kind: Dict[str, Dict[str, int]] = {}
    by_owner: Dict[str, Dict[str, int]] = {}
    by_node: Dict[str, Dict[str, Any]] = {}
    suspects: List[dict] = []
    total = 0
    ready = 0
    for row in objs:
        size = row.get("size_bytes") or row.get("size") or 0
        kind = row.get("reference_kind") or "owned"
        owner = row.get("owner") or "<unknown>"
        node = row.get("node_id") or "<node>"
        nrec = by_node.setdefault(node, {
            "count": 0, "bytes": 0, "shm_bytes": 0, "by_kind": {}})
        if row.get("state") != "ready":
            continue
        ready += 1
        total += size
        kcell = by_kind.setdefault(kind, {"count": 0, "bytes": 0})
        kcell["count"] += 1
        kcell["bytes"] += size
        ocell = by_owner.setdefault(owner, {"count": 0, "bytes": 0})
        ocell["count"] += 1
        ocell["bytes"] += size
        nrec["count"] += 1
        nrec["bytes"] += size
        if row.get("loc") == "shm":
            nrec["shm_bytes"] += size
        nk = nrec["by_kind"].setdefault(kind, {"count": 0, "bytes": 0})
        nk["count"] += 1
        nk["bytes"] += size
        age = row.get("age_s") or 0.0
        if age < leak_min_age_s:
            continue
        reason = None
        if (kind in ("owned", "spilled")
                and row.get("owner")
                and row["owner"] not in live_clients):
            reason = "owner client is dead"
        elif kind == "borrowed" and (row.get("refcount") or 0) <= 0:
            reason = "borrowed replica with zero borrow count"
        if reason is not None:
            suspects.append(dict(row, leak_reason=reason))
    for node, store in stores.items():
        nrec = by_node.setdefault(node, {
            "count": 0, "bytes": 0, "shm_bytes": 0, "by_kind": {}})
        nrec["store_used_bytes"] = store.get("used_bytes", 0)
        nrec["store_capacity_bytes"] = store.get("capacity_bytes", 0)
        nrec["store_num_objects"] = store.get("num_objects", 0)
    suspects.sort(key=lambda r: -(r.get("size_bytes") or 0))
    top = sorted((r for r in objs if r.get("state") == "ready"),
                 key=lambda r: -(r.get("size_bytes") or 0))[:top_n]
    kv_blocks: Dict[str, float] = {}
    try:
        from ray_tpu.util import metrics as _metrics
        for s in _metrics.scrape():
            if s.get("name") == _metrics.KV_BLOCKS_METRIC:
                st = (s.get("tags") or {}).get("state", "unknown")
                kv_blocks[st] = kv_blocks.get(st, 0) + (
                    s.get("value") or 0)
    except Exception:
        pass
    return {
        "total_bytes": total,
        "object_count": ready,
        "by_kind": by_kind,
        "by_owner": by_owner,
        "by_node": by_node,
        "leak_suspects": suspects,
        "objects": top,
        "kv_blocks": kv_blocks,
        "unreachable_nodes": dump.get("unreachable_nodes") or [],
    }


def summarize_scheduling() -> Dict[str, Any]:
    """Cluster-merged scheduler decision rollup.

    Every placement decision the raylet scheduler makes is recorded
    at the decision point (outcome = local / forward / spill / queue /
    drain_handback / infeasible, with the detail the scorer saw —
    spill candidates, locality targets, queue reasons); this merges
    the per-node tallies plus each node's recent-decision ring:

    * outcomes: cluster-wide {outcome: count};
    * decisions: total decisions recorded;
    * pending: tasks currently sitting in pending queues;
    * recent: the newest decision rows across all nodes (each carries
      node_id, task name, outcome, and outcome-specific detail like
      spill candidate scores);
    * by_node: the unmerged per-node view.

    The same counts surface as ``ray_tpu_sched_decisions_total`` and
    the placement-latency histogram
    ``ray_tpu_sched_placement_seconds``."""
    dump = _dump()
    sched = dump.get("scheduling") or {}
    outcomes: Dict[str, int] = {}
    recent: List[dict] = []
    pending = 0
    for node, s in sched.items():
        for k, v in (s.get("outcomes") or {}).items():
            outcomes[k] = outcomes.get(k, 0) + int(v)
        pending += int(s.get("pending") or 0)
        for row in s.get("recent") or []:
            recent.append(dict(row, node_id=node))
    recent.sort(key=lambda r: r.get("ts") or 0.0)
    return {
        "outcomes": outcomes,
        "decisions": sum(outcomes.values()),
        "pending": pending,
        "recent": recent[-100:],
        "by_node": sched,
        "unreachable_nodes": dump.get("unreachable_nodes") or [],
    }


def metric_history(name: Optional[str] = None,
                   cluster: bool = True) -> Dict[str, Any]:
    """Recent (ts, value) samples per metric series from the bounded
    per-node history rings (``metrics_history_resolution_s`` sample
    cadence, ``metrics_history_window_s`` retention).

    Counters and histograms sample their running total/observation
    count (rate = delta over the window); gauges sample the last set
    value.  Each series row: {name, kind, tags, node_id, samples:
    [[ts, value], ...]}.  With `name`, only that metric's series;
    with cluster=True (default), merged across every alive node (a
    concat — rows keep their node_id).  The same data serves
    ``/api/metrics/history`` and the ``ray_tpu top`` live view."""
    reply = _client().conn.call({"type": "metric_history",
                                 "name": name, "cluster": cluster})
    return {"series": reply.get("series") or [],
            "unreachable_nodes": reply.get("unreachable_nodes") or []}


def doctor(leak_min_age_s: float = 60.0,
           gcs_stale_s: float = 15.0,
           sync_hot_count: int = 100) -> Dict[str, Any]:
    """Cluster health triage: one call that fuses the control-plane
    signals (GCS liveness + WAL health, node reachability, stall
    sentinel, slow-RPC captures, leak suspects, event-ring drops,
    lock contention, serve shedding, train goodput) into a prioritized
    findings list — the engine behind ``ray_tpu doctor`` and
    ``/api/doctor``.

    Returns {"healthy", "exit_code", "findings", "probes"}.  Each
    finding: {"code", "severity" ("error" | "warning"), "summary",
    "detail"}.  Stable codes:

    * errors (exit_code 1): GCS_UNREACHABLE (a node's last successful
      GCS heartbeat is older than `gcs_stale_s`; multinode only —
      single-node mode has no heartbeat loop), NODE_UNREACHABLE
      (registered-alive peer did not answer the health probe),
      TASK_STALLED (stall-sentinel capture in the event ring),
      LEAK_SUSPECT (READY object at least `leak_min_age_s` old whose
      owner is dead or whose borrow count hit zero);
    * warnings (exit_code stays 0): EVENT_RING_DROPS, SLOW_RPC,
      GCS_WAL_LARGE (WAL > 4x gcs_wal_compact_bytes),
      GCS_SNAPSHOT_STALE (ops since snapshot > 4x
      gcs_wal_compact_ops), LOCK_CONTENTION (locksan witnessed a
      lock-order inversion), SERVE_SHEDDING (admission control shed
      requests), TRAIN_GOODPUT_LOW (productive fraction of an
      instrumented run's wall clock below 50%), GANG_RESIZE_THRASH
      (an elastic run resized more often than
      ``train_resize_thrash_per_min`` — capacity is flapping faster
      than resharding can amortize; raise the grace window or stop
      growing back), RECOMPILE_STORM (an
      xlasan jit site recompiled past its budget — from the merged
      ledger, with the ``ray_tpu_xla_recompiles_total`` metrics-
      history ring as fallback for processes that died before their
      dump), HOST_SYNC_HOT_LOOP (an xlasan-witnessed
      block_until_ready/device_get call site fired at least
      `sync_hot_count` times — a per-iteration host fence).

    Probes run independently — one failing (its subsystem not in use,
    its sanitizer not enabled) records a probe error and the rest
    still report."""
    from ray_tpu._private.config import config

    findings: List[dict] = []
    probe_errors: List[dict] = []
    probes: List[str] = []

    def _probe(name):
        probes.append(name)

    # -- control-plane health cards (per node) -------------------------
    _probe("health_probe")
    gcs_down = False
    try:
        reply = _client().conn.call({"type": "health_probe",
                                     "cluster": True})
        nodes = reply.get("nodes") or []
        unreachable = reply.get("unreachable_nodes") or []
        if unreachable:
            findings.append({
                "code": "NODE_UNREACHABLE", "severity": "error",
                "summary": (f"{len(unreachable)} registered-alive "
                            "node(s) did not answer the health probe"),
                "detail": {"nodes": unreachable}})
        stale = [n for n in nodes
                 if n.get("multinode")
                 and (n.get("gcs_last_ok_age_s") or 0.0) > gcs_stale_s]
        if stale:
            gcs_down = True
            worst = max(n["gcs_last_ok_age_s"] for n in stale)
            findings.append({
                "code": "GCS_UNREACHABLE", "severity": "error",
                "summary": (f"{len(stale)} node(s) have not heard "
                            f"from the GCS in over {gcs_stale_s:.0f}s "
                            f"(worst {worst:.1f}s)"),
                "detail": {"nodes": [
                    {"node_id": n["node_id"],
                     "age_s": n["gcs_last_ok_age_s"]} for n in stale]}})
        dropped = sum(float(n.get("events_dropped") or 0.0)
                      for n in nodes)
        if dropped > 0:
            findings.append({
                "code": "EVENT_RING_DROPS", "severity": "warning",
                "summary": (f"{int(dropped)} lifecycle/profile events "
                            "evicted from bounded event rings — raise "
                            "profile_events_max for full history"),
                "detail": {"dropped_total": dropped}})
        slow = {}
        for n in nodes:
            for meth, cnt in (n.get("slow_rpcs") or {}).items():
                slow[meth] = slow.get(meth, 0) + int(cnt)
        if slow:
            findings.append({
                "code": "SLOW_RPC", "severity": "warning",
                "summary": ("slow-RPC sentinel fired for "
                            + ", ".join(sorted(slow))
                            + " — stacks in the timeline "
                            "(kind=slow_rpc)"),
                "detail": {"by_method": slow}})
        gst = {}
        for n in nodes:
            gst = n.get("gcs_status") or {}
            if gst:
                break
        if gst.get("persistent"):
            wal_bytes = int(gst.get("wal_bytes") or 0)
            if wal_bytes > 4 * config.gcs_wal_compact_bytes:
                findings.append({
                    "code": "GCS_WAL_LARGE", "severity": "warning",
                    "summary": (f"GCS WAL is {wal_bytes} bytes, over "
                                "4x the compaction threshold — "
                                "compaction may not be firing"),
                    "detail": {"wal_bytes": wal_bytes,
                               "compact_bytes":
                                   config.gcs_wal_compact_bytes}})
            wal_ops = int(gst.get("wal_ops_since_snapshot") or 0)
            if wal_ops > 4 * config.gcs_wal_compact_ops:
                findings.append({
                    "code": "GCS_SNAPSHOT_STALE", "severity": "warning",
                    "summary": (f"{wal_ops} durable ops since the last "
                                "GCS snapshot, over 4x the compaction "
                                "threshold"),
                    "detail": {
                        "wal_ops_since_snapshot": wal_ops,
                        "compact_ops": config.gcs_wal_compact_ops,
                        "last_snapshot_age_s":
                            gst.get("last_snapshot_age_s")}})
    except Exception as exc:   # noqa: BLE001 - probe isolation
        probe_errors.append({"probe": "health_probe",
                             "error": repr(exc)})

    # -- stall sentinel (event ring) -----------------------------------
    _probe("stalls")
    try:
        stalls = [ev for ev in _client().timeline_events(cluster=True)
                  if ev.get("kind") == "stall"]
        if stalls:
            findings.append({
                "code": "TASK_STALLED", "severity": "error",
                "summary": (f"stall sentinel captured {len(stalls)} "
                            "long-running task(s) — stacks attached"),
                "detail": {"stalls": [
                    {k: ev.get(k) for k in
                     ("task_name", "task_id", "elapsed_s",
                      "threshold_s", "node_id", "pid")}
                    for ev in stalls[-10:]]}})
    except Exception as exc:   # noqa: BLE001
        probe_errors.append({"probe": "stalls", "error": repr(exc)})

    # -- object-store leak suspects ------------------------------------
    _probe("memory")
    try:
        mem = memory_summary(leak_min_age_s=leak_min_age_s, top_n=10)
        suspects = mem.get("leak_suspects") or []
        if suspects:
            findings.append({
                "code": "LEAK_SUSPECT", "severity": "error",
                "summary": (f"{len(suspects)} object(s) look leaked "
                            "(dead owner or zero borrow count, age ≥ "
                            f"{leak_min_age_s:.0f}s)"),
                "detail": {"suspects": [
                    {k: r.get(k) for k in
                     ("object_id", "size_bytes", "owner",
                      "reference_kind", "age_s", "leak_reason")}
                    for r in suspects[:10]]}})
    except Exception as exc:   # noqa: BLE001
        probe_errors.append({"probe": "memory", "error": repr(exc)})

    # -- lock-order inversions (needs RAY_TPU_LOCKSAN=1 runs) ----------
    _probe("locksan")
    try:
        rep = locksan_report()
        inv = rep.get("inversions") or []
        if inv:
            findings.append({
                "code": "LOCK_CONTENTION", "severity": "warning",
                "summary": (f"locksan witnessed {len(inv)} lock-order "
                            "inversion(s) — each a deadlock under the "
                            "right timing"),
                "detail": {"inversions": inv[:5]}})
    except Exception as exc:   # noqa: BLE001
        probe_errors.append({"probe": "locksan", "error": repr(exc)})

    # -- serve admission shedding --------------------------------------
    _probe("serve")
    try:
        from ray_tpu.util.metrics import SERVE_REQUESTS_SHED_METRIC
        shed = 0.0
        for row in metric_history(
                name=SERVE_REQUESTS_SHED_METRIC)["series"]:
            samples = row.get("samples") or []
            if samples:
                shed += float(samples[-1][1])
        if shed > 0:
            findings.append({
                "code": "SERVE_SHEDDING", "severity": "warning",
                "summary": (f"serve admission control has shed "
                            f"{int(shed)} request(s) — deployments "
                            "are over capacity"),
                "detail": {"requests_shed": shed}})
    except Exception as exc:   # noqa: BLE001
        probe_errors.append({"probe": "serve", "error": repr(exc)})

    # -- XLA recompile storms / hot host syncs (RAY_TPU_XLASAN=1) ------
    _probe("xlasan")
    try:
        rep = xlasan_report()
        storm_detail = {
            s: rep["sites"][s] for s in rep.get("storms") or []
            if s in (rep.get("sites") or {})}
        # Metrics-history fallback: a worker that died before its
        # atexit dump still streamed per-site recompile counts into
        # the PR-16 history ring.
        try:
            from ray_tpu.util.metrics import XLA_RECOMPILES_METRIC
            budget = int(rep.get("budget") or 2)
            for row in metric_history(
                    name=XLA_RECOMPILES_METRIC)["series"]:
                samples = row.get("samples") or []
                site = (row.get("tags") or {}).get("site", "?")
                if samples and float(samples[-1][1]) > budget \
                        and site not in storm_detail:
                    storm_detail[site] = {
                        "recompiles": float(samples[-1][1]),
                        "source": "metrics_history"}
        except Exception:   # noqa: BLE001 - ring needs a live runtime
            pass
        if storm_detail:
            worst = max(storm_detail,
                        key=lambda s: storm_detail[s].get(
                            "recompiles", 0))
            findings.append({
                "code": "RECOMPILE_STORM", "severity": "warning",
                "summary": (f"{len(storm_detail)} jit site(s) "
                            "recompiled past the xlasan budget "
                            f"(worst: {worst} x"
                            f"{storm_detail[worst].get('recompiles')})"
                            " — see `ray_tpu xlasan` for arg-shape "
                            "deltas"),
                "detail": {"budget": rep.get("budget"),
                           "sites": dict(list(
                               storm_detail.items())[:10])}})
        hot_syncs = {
            s: r for s, r in (rep.get("syncs") or {}).items()
            if int(r.get("count") or 0) >= sync_hot_count}
        if hot_syncs:
            findings.append({
                "code": "HOST_SYNC_HOT_LOOP", "severity": "warning",
                "summary": (f"{len(hot_syncs)} call site(s) fenced "
                            f"the host ≥{sync_hot_count} times "
                            "(block_until_ready/device_get in a "
                            "loop) — accumulate device-side and "
                            "convert once"),
                "detail": {"sites": dict(list(
                    hot_syncs.items())[:10])}})
    except Exception as exc:   # noqa: BLE001
        probe_errors.append({"probe": "xlasan", "error": repr(exc)})

    # -- train goodput --------------------------------------------------
    # Telemetry snapshots live in the control-plane KV, whose node-side
    # proxy BLOCKS while the GCS is down — with the GCS already flagged
    # stale, skip rather than hang the whole triage behind it.
    _probe("train")
    try:
        if gcs_down:
            raise RuntimeError(
                "skipped: control-plane KV unreachable (GCS stale)")
        # Liveness ping with a short client-side deadline: right after
        # a GCS death the health ages may not have crossed gcs_stale_s
        # yet, and the first unguarded KV read would sit behind the
        # proxy's full reconnect backoff (up to a minute).
        try:
            _client().conn.call(
                {"type": "kv_keys", "ns": "__train_runs__",
                 "prefix": b""}, timeout=2.0)
        except TimeoutError:
            raise RuntimeError(
                "skipped: control-plane KV unreachable "
                "(liveness ping timed out)") from None
        runs = (train_summary() or {}).get("runs") or {}
        for run, roll in runs.items():
            ledger = roll.get("ledger") or {}
            total = sum(float(v) for v in ledger.values())
            productive = float(ledger.get("productive") or 0.0)
            if total >= 10.0 and productive / total < 0.5:
                findings.append({
                    "code": "TRAIN_GOODPUT_LOW", "severity": "warning",
                    "summary": (f"train run {run!r}: only "
                                f"{100 * productive / total:.0f}% of "
                                "instrumented wall clock was "
                                "productive step time"),
                    "detail": {"run": run,
                               "verdict": roll.get("verdict"),
                               "ledger": ledger}})
            resizes = int(roll.get("resize_count") or 0)
            wall = float(roll.get("wall_s") or 0.0)
            thrash = float(config.train_resize_thrash_per_min)
            if (resizes >= 2 and wall > 0 and thrash > 0
                    and resizes / (wall / 60.0) > thrash):
                findings.append({
                    "code": "GANG_RESIZE_THRASH",
                    "severity": "warning",
                    "summary": (f"train run {run!r}: "
                                f"{resizes} elastic resizes in "
                                f"{wall:.0f}s of instrumented wall "
                                f"clock (> {thrash:g}/min) — "
                                "capacity is flapping faster than "
                                "resharding amortizes"),
                    "detail": {"run": run, "resizes": resizes,
                               "wall_s": wall,
                               "per_min": resizes / (wall / 60.0),
                               "events": (roll.get("resizes")
                                          or [])[-8:]}})
    except Exception as exc:   # noqa: BLE001
        probe_errors.append({"probe": "train", "error": repr(exc)})

    sev_rank = {"error": 0, "warning": 1}
    findings.sort(key=lambda f: (sev_rank.get(f["severity"], 2),
                                 f["code"]))
    errors = any(f["severity"] == "error" for f in findings)
    return {
        "healthy": not errors,
        "exit_code": 1 if errors else 0,
        "findings": findings,
        "probes": probes,
        "probe_errors": probe_errors,
    }
