"""Public runtime surface of the chaos-injection subsystem.

Thin wrapper over ``ray_tpu._private.chaos`` (the seeded schedule) plus
the node-service hooks that need a live session.  Unlike the frozen
env-spec of the original ``protocol._Chaos``, faults can be armed and
cleared at runtime::

    from ray_tpu.util import chaos

    chaos.inject("dispatch", kind="kill_worker", n=1)   # next dispatch
    chaos.inject("get_objects", kind="drop", p=0.2, n=5)
    ...
    chaos.clear()
    print(chaos.trace())     # [(seq, site, kind), ...] — replay witness

State is per-process: single-node, the node service runs inside the
driver, so driver-side ``inject()`` drives node-level faults directly.
Workers inherit the env/config spec (``RAY_TPU_CHAOS_SPEC`` +
``RAY_TPU_CHAOS_SEED``) at spawn.  See ``_private/chaos.py`` for the
spec grammar and fault-kind semantics; ``ray_tpu chaos`` validates a
spec from the CLI.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.chaos import FAULT_KINDS, chaos as _chaos
from ray_tpu._private.chaos import parse_spec  # noqa: F401  (CLI/tests)

__all__ = ["inject", "clear", "trace", "reset_trace", "refresh",
           "describe", "evict_object", "parse_spec", "FAULT_KINDS"]


def inject(site: str, kind: str = "error", p: float = 1.0, n: int = -1,
           lo_ms: float = 0.0, hi_ms: float = 0.0,
           node: str = "", deadline_s: float = 0.0,
           down_s: float = 0.0, interval_s: float = 0.0) -> None:
    """Arm a fault at runtime (this process).  Raises ValueError for an
    invalid kind/probability/bounds combination.  ``n`` + ``interval_s``
    describe a whole storm: n firings at least interval_s apart."""
    _chaos.inject(site, kind=kind, p=p, n=n, lo_ms=lo_ms, hi_ms=hi_ms,
                  node=node, deadline_s=deadline_s, down_s=down_s,
                  interval_s=interval_s)


def clear(site: Optional[str] = None) -> None:
    """Disarm runtime-injected faults (all of them, or one site's)."""
    _chaos.clear(site)


def trace() -> List[Tuple[int, str, str]]:
    """The injected-fault trace: [(seq, site, kind), ...].  Two runs of
    one workload with the same ``chaos_seed`` produce identical
    traces — assert equality to prove a failure schedule replays."""
    return _chaos.trace()


def reset_trace() -> None:
    _chaos.reset_trace()


def refresh() -> None:
    """Force immediate re-resolution of the env/config schedule (it is
    otherwise re-checked lazily, at most every 250 ms)."""
    _chaos.refresh()


def describe() -> List[Dict[str, Any]]:
    """The currently-armed fault specs (env/config + runtime)."""
    return _chaos.describe()


def evict_object(ref) -> bool:
    """Evict a READY object's shm payload from the local store while
    keeping its directory entry — the store-eviction fault, aimed at
    one object.  The next reader hits the lineage-reconstruction path
    (``node_objects._try_reconstruct``).  Returns False when the object
    is not eligible (not READY, not in shm, or has no lineage)."""
    import ray_tpu
    client = ray_tpu._ensure_connected()
    return bool(client.conn.call({"type": "chaos_evict",
                                  "object_id": ref.binary()})["ok"])
