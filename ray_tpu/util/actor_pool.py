"""ActorPool: work distribution over a fixed set of actors
(reference: python/ray/util/actor_pool.py — submit/get_next/
get_next_unordered/map/map_unordered/has_next/has_free/push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]) -> None:
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor: dict = {}
        self._pending: List[Any] = []       # submission order (refs)
        # (fn, value) submissions waiting for a free actor (reference:
        # _pending_submits — submit() queues when the pool is busy).
        self._queued: List[tuple] = []

    # -- submission ----------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; runs on the next free actor,
        or queues until one frees up (reference semantics)."""
        if not self._idle:
            self._queued.append((fn, value))
            return
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref.binary()] = actor
        self._pending.append(ref)

    def _drain_queued(self) -> None:
        while self._queued and self._idle:
            fn, value = self._queued.pop(0)
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._idle)

    def has_next(self) -> bool:
        return bool(self._pending) or bool(self._queued)

    # -- results -------------------------------------------------------
    def _finish(self, ref) -> Any:
        actor = self._future_to_actor.pop(ref.binary(), None)
        if actor is not None:
            self._idle.append(actor)
        self._pending.remove(ref)
        self._drain_queued()        # a freed actor admits queued work
        return ray_tpu.get(ref)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        if not self._pending:
            raise StopIteration("no pending results")
        ref = self._pending[0]
        done, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not done:
            raise TimeoutError("get_next timed out")
        return self._finish(ref)

    def get_next_unordered(self,
                           timeout: Optional[float] = None) -> Any:
        """Whichever pending result completes first."""
        if not self._pending:
            raise StopIteration("no pending results")
        done, _ = ray_tpu.wait(list(self._pending), num_returns=1,
                               timeout=timeout)
        if not done:
            raise TimeoutError("get_next_unordered timed out")
        return self._finish(done[0])

    # -- bulk ----------------------------------------------------------
    def _map(self, fn, values, getter):
        values = iter(values)
        exhausted = False
        while True:
            while not exhausted and self.has_free():
                try:
                    self.submit(fn, next(values))
                except StopIteration:
                    exhausted = True
            if not self.has_next():
                return
            yield getter()

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]):
        """Ordered streaming map keeping every actor busy."""
        return self._map(fn, values, self.get_next)

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        """Completion-order streaming map."""
        return self._map(fn, values, self.get_next_unordered)

    # -- membership ----------------------------------------------------
    def push(self, actor: Any) -> None:
        self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop(0) if self._idle else None
