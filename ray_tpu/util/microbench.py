"""Core-runtime microbenchmarks, JSON-logged.

Analog of the reference's microbenchmark driver
(python/ray/_private/ray_perf.py:93, `ray microbenchmark` CLI) whose
published numbers are the BASELINE.md table (release_logs/2.9.3/
microbenchmark.json): sync/async actor calls/s, task throughput, object
put rate and bandwidth, get latency.

Run: python -m ray_tpu.util.microbench [--out FILE]
Prints one JSON object; with --out also writes it to FILE.
"""

from __future__ import annotations

import argparse
import json
import time


def _rate(n: int, dt: float) -> float:
    return round(n / dt, 1)


def _settle(ray_tpu, *actors) -> None:
    """Kill a bench's actors NOW and give teardown a beat — handle-GC
    release churn (worker kills) must not run inside the next bench's
    timed window."""
    for a in actors:
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    time.sleep(0.2)


def bench_actor_calls_sync(ray_tpu, n: int = 300) -> float:
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def inc(self):
            self.x += 1
            return self.x

    a = Counter.remote()
    ray_tpu.get(a.inc.remote())  # warm: actor alive, worker hot
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.inc.remote())
    rate = _rate(n, time.perf_counter() - t0)
    _settle(ray_tpu, a)
    return rate


def bench_actor_calls_async(ray_tpu, n: int = 2000) -> float:
    """Pipelined (submit all, then drain) — the reference's 'async' mode."""
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return 1

    a = Echo.remote()
    ray_tpu.get(a.ping.remote())
    t0 = time.perf_counter()
    refs = [a.ping.remote() for _ in range(n)]
    ray_tpu.get(refs[-1])   # single-threaded actor: strictly in order
    rate = _rate(n, time.perf_counter() - t0)
    _settle(ray_tpu, a)
    return rate


def bench_actor_calls_concurrent(ray_tpu, n: int = 2000) -> float:
    """Pipelined calls against a max_concurrency actor (reference:
    1_1_actor_calls_concurrent — threaded actor, overlapping calls)."""
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return 1

    a = Echo.options(max_concurrency=8).remote()
    ray_tpu.get(a.ping.remote())
    t0 = time.perf_counter()
    refs = [a.ping.remote() for _ in range(n)]
    # Wait on ALL refs: a concurrent actor finishes out of order, so
    # refs[-1] alone would stop the clock with calls still running.
    ray_tpu.get(refs)
    rate = _rate(n, time.perf_counter() - t0)
    _settle(ray_tpu, a)
    return rate


def bench_one_to_n_actor_calls(ray_tpu, n_actors: int = 4,
                               calls: int = 500) -> float:
    """One caller fanning out over N actors (reference:
    1_n_actor_calls_async)."""
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return 1

    actors = [Echo.remote() for _ in range(n_actors)]
    ray_tpu.get([a.ping.remote() for a in actors])
    t0 = time.perf_counter()
    refs = [actors[i % n_actors].ping.remote()
            for i in range(calls * n_actors)]
    ray_tpu.get(refs)
    rate = _rate(calls * n_actors, time.perf_counter() - t0)
    _settle(ray_tpu, *actors)
    return rate


def bench_n_to_n_actor_calls(ray_tpu, n_pairs: int = 4,
                             calls: int = 400) -> float:
    """N caller actors each driving their own callee (reference:
    n_n_actor_calls_async): measures dispatch-plane aggregate, not a
    single pair."""
    @ray_tpu.remote
    class Echo:
        def ping(self):
            return 1

    @ray_tpu.remote
    class Caller:
        def __init__(self, target):
            self._t = target

        def drive(self, n):
            import ray_tpu as rt
            refs = [self._t.ping.remote() for _ in range(n)]
            rt.get(refs)
            return n

    # Zero-CPU actors: the bench measures the dispatch plane, and
    # 2*n_pairs default-CPU actors would deadlock on a small host
    # (callers hold every slot, callees never schedule).
    callees = [Echo.options(num_cpus=0).remote()
               for _ in range(n_pairs)]
    callers = [Caller.options(num_cpus=0).remote(c) for c in callees]
    ray_tpu.get([c.drive.remote(5) for c in callers])   # warm
    t0 = time.perf_counter()
    done = ray_tpu.get([c.drive.remote(calls) for c in callers])
    rate = _rate(sum(done), time.perf_counter() - t0)
    _settle(ray_tpu, *(callers + callees))
    return rate


def bench_tasks_async(ray_tpu, n: int = 500) -> float:
    @ray_tpu.remote
    def nop():
        return 1

    # Warm the worker pool to steady state first (the reference's
    # harness also excludes pool growth from the measured window).
    ray_tpu.get([nop.remote() for _ in range(100)])
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n)]
    ray_tpu.get(refs)
    return _rate(n, time.perf_counter() - t0)


def bench_put_small(ray_tpu, n: int = 2000) -> float:
    payload = b"x" * 1024
    ray_tpu.put(payload)
    t0 = time.perf_counter()
    refs = [ray_tpu.put(payload) for _ in range(n)]
    dt = time.perf_counter() - t0
    del refs
    return _rate(n, dt)


def bench_put_gbps(ray_tpu, n: int = 10, mb: int = 64) -> float:
    import numpy as np
    payload = np.random.bytes(mb * 1024 * 1024)
    r = ray_tpu.put(payload)
    del r
    t0 = time.perf_counter()
    for _ in range(n):
        # Drop each ref immediately so the directory can free the entry;
        # holding all n would need n*mb of live store.
        r = ray_tpu.put(payload)
        del r
    dt = time.perf_counter() - t0
    return round(n * mb / 1024 / dt, 2)


def bench_multi_client_put_gbps(ray_tpu, clients: int = 4, n: int = 6,
                                mb: int = 32) -> float:
    """Aggregate put bandwidth of N separate PROCESSES writing
    concurrently (reference: multi_client_put_gigabytes, 35.9 GB/s on
    64 cores).  This is the benchmark the broker-less design exists
    for: every writer maps the shared segment and memcpys directly —
    no per-put server round-trip to serialize on (the reference's
    plasma store brokers every create through the store thread)."""
    @ray_tpu.remote
    class Putter:
        def __init__(self, mb: int) -> None:
            # Imported here, not in the enclosing scope: a closure-
            # captured module rides the pickled actor spec (RT002).
            import numpy as np
            self.payload = np.random.bytes(mb * 1024 * 1024)

        def warm(self) -> int:
            r = ray_tpu.put(self.payload)  # noqa: F841
            return 1

        def put_n(self, n: int) -> float:
            t0 = time.perf_counter()
            for _ in range(n):
                r = ray_tpu.put(self.payload)
                del r     # drop so the segment can recycle the space
            return time.perf_counter() - t0

    actors = [Putter.remote(mb) for _ in range(clients)]
    ray_tpu.get([a.warm.remote() for a in actors])
    t0 = time.perf_counter()
    ray_tpu.get([a.put_n.remote(n) for a in actors])
    wall = time.perf_counter() - t0
    _settle(ray_tpu, *actors)
    return round(clients * n * mb / 1024 / wall, 2)


def bench_multi_client_put_small(ray_tpu, clients: int = 4,
                                 n: int = 300) -> float:
    """Aggregate small-put rate of N concurrent processes (reference:
    multi_client_put_calls_Plasma_Store, 12,677/s on 64 cores)."""

    @ray_tpu.remote
    class Putter:
        def warm(self) -> int:
            ray_tpu.put(b"x" * 1024)
            return 1

        def put_n(self, n: int) -> float:
            payload = b"x" * 1024
            t0 = time.perf_counter()
            for _ in range(n):
                r = ray_tpu.put(payload)
                del r
            return time.perf_counter() - t0

    actors = [Putter.remote() for _ in range(clients)]
    ray_tpu.get([a.warm.remote() for a in actors])
    t0 = time.perf_counter()
    ray_tpu.get([a.put_n.remote(n) for a in actors])
    wall = time.perf_counter() - t0
    _settle(ray_tpu, *actors)
    return _rate(clients * n, wall)


def bench_get_latency_us(ray_tpu, n: int = 1000) -> float:
    """Median latency of get() on a small plasma-resident object."""
    import numpy as np
    ref = ray_tpu.put(np.arange(64 * 1024, dtype=np.uint8))  # shm-resident
    ray_tpu.get(ref)
    lats = []
    for _ in range(n):
        t0 = time.perf_counter()
        ray_tpu.get(ref)
        lats.append(time.perf_counter() - t0)
    lats.sort()
    return round(lats[n // 2] * 1e6, 1)


def bench_thin_client_sync(n: int = 500) -> float:
    """1:1 sync actor calls THROUGH the thin client (reference:
    client__1_1_actor_calls_sync, 515/s on m5.16xlarge) — run in a
    subprocess so the client is a genuinely separate process speaking
    TCP to the cluster node."""
    import subprocess
    import sys
    import textwrap

    import ray_tpu
    node = ray_tpu._session.node_service
    if not node.multinode:
        return 0.0
    addr = f"127.0.0.1:{node.control_port}"

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def inc(self):
            self.x += 1
            return self.x

    # Named detached actor: the handle is re-fetched by name in the
    # child process, so dropping this one is deliberate.
    Counter.options(  # ray-tpu: noqa[RT006]
        name="_mb_counter", lifetime="detached").remote()
    script = textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, {__file__.rsplit('/ray_tpu/', 1)[0]!r})
        from ray_tpu.util import client
        import ray_tpu
        client.connect({addr!r})
        a = ray_tpu.get_actor("_mb_counter")
        ray_tpu.get(a.inc.remote())
        t0 = time.perf_counter()
        for _ in range({n}):
            ray_tpu.get(a.inc.remote())
        print("RATE", {n} / (time.perf_counter() - t0))
        client.disconnect()
    """)
    import os
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=300)
    for line in r.stdout.splitlines():
        if line.startswith("RATE "):
            return round(float(line.split()[1]), 1)
    raise RuntimeError(
        f"thin-client benchmark subprocess failed "
        f"(rc={r.returncode}):\n{r.stderr[-2000:]}")


def run_all(out_path: str | None = None) -> dict:
    import ray_tpu

    # Phase 1: single-node mode — the core hot paths with no GCS hop.
    ray_tpu.init(num_cpus=4, object_store_memory=1 << 30,
                 ignore_reinit_error=True)
    # Object/task benches FIRST: actor benches release their actors
    # on return (handle GC) and the resulting worker churn would
    # contaminate measurements taken while it settles.
    results = {
        "tasks_async_per_s": bench_tasks_async(ray_tpu),
        "put_small_per_s": bench_put_small(ray_tpu),
        "put_gigabytes_per_s": bench_put_gbps(ray_tpu),
        "multi_client_put_gigabytes_per_s":
            bench_multi_client_put_gbps(ray_tpu),
        "multi_client_put_per_s": bench_multi_client_put_small(ray_tpu),
        "get_64kb_median_us": bench_get_latency_us(ray_tpu),
        "actor_calls_sync_per_s": bench_actor_calls_sync(ray_tpu),
        "actor_calls_async_per_s": bench_actor_calls_async(ray_tpu),
        "actor_calls_concurrent_per_s":
            bench_actor_calls_concurrent(ray_tpu),
        "one_to_n_actor_calls_per_s":
            bench_one_to_n_actor_calls(ray_tpu),
        "n_to_n_actor_calls_per_s":
            bench_n_to_n_actor_calls(ray_tpu),
    }
    ray_tpu.shutdown()

    # Phase 2: multinode head — the thin client needs the TCP endpoint.
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster()
    ray_tpu.init(num_cpus=4, gcs_address=cluster.gcs_address)
    try:
        results["client_actor_calls_sync_per_s"] = \
            bench_thin_client_sync()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    results.update({
        "note": ("this host: 1 vCPU, single client; reference numbers "
                 "are m5.16xlarge (64 vCPU) with multi-client "
                 "aggregation for put/task rates"),
        "reference_baseline": {
            # release_logs/2.9.3/microbenchmark.json on m5.16xlarge
            # (64 vCPU); this host has 1 vCPU — rates here are
            # single-core, the reference's are 64-core.
            "actor_calls_sync_per_s": 2033,
            "actor_calls_async_per_s": 8886,
            "actor_calls_concurrent_per_s": 5095,
            "one_to_n_actor_calls_per_s": 8570,
            "n_to_n_actor_calls_per_s": 27667,
            "multi_client_tasks_async_per_s": 25166,
            "multi_client_put_per_s": 12677,
            "multi_client_put_gigabytes_per_s": 35.9,
            "client_actor_calls_sync_per_s": 515,
        },
    })
    blob = json.dumps(results, indent=1)
    print(blob)
    if out_path:
        with open(out_path, "w") as f:
            f.write(blob + "\n")
    return results


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    run_all(ap.parse_args().out)
