"""joblib backend: scikit-learn `Parallel` jobs on the cluster.

Reference surface: python/ray/util/joblib/ (register_ray +
RayBackend over the multiprocessing-pool shim).  Usage:

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        Parallel(n_jobs=8)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations

import ray_tpu


def register_ray() -> None:
    from joblib._parallel_backends import MultiprocessingBackend
    from joblib.parallel import register_parallel_backend

    from ray_tpu.util.multiprocessing import Pool

    class RayTpuBackend(MultiprocessingBackend):
        """joblib backend whose pool is the cluster-wide task Pool."""

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs == -1:
                return max(cpus, 1)
            return min(max(n_jobs, 1), max(cpus, 1))

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            self.parallel = parallel
            self._pool = Pool(processes=n_jobs)
            return n_jobs

        def _get_pool(self):
            return self._pool

        def terminate(self):
            # Deliberately NOT calling MultiprocessingBackend.terminate:
            # it manipulates stdlib-pool internals ours doesn't have.
            pool = getattr(self, "_pool", None)
            if pool is not None:
                pool.terminate()

    register_parallel_backend("ray_tpu", RayTpuBackend)
