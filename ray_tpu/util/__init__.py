"""Cluster utilities (reference: python/ray/util)."""

from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          placement_group_table,
                                          remove_placement_group,
                                          tpu_slice_bundles)

__all__ = [
    "PlacementGroup", "placement_group", "remove_placement_group",
    "placement_group_table", "tpu_slice_bundles",
]

from ray_tpu.util.actor_pool import ActorPool  # noqa: E402,F401
