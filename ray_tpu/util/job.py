"""Job submission: run an entrypoint command on the cluster.

Reference surface: python/ray/dashboard/modules/job/sdk.py
(JobSubmissionClient.submit_job/stop_job/get_job_status/get_job_logs)
backed by the JobSupervisor actor pattern
(modules/job/job_supervisor.py): a detached, zero-CPU supervisor actor
runs the entrypoint as a child process on some cluster node, streams its
combined output and status transitions into GCS KV, and survives the
submitting client.

The child process inherits `RAY_TPU_GCS_ADDRESS`, so a plain
`ray_tpu.init()` inside the job script joins the same cluster
(reference: RAY_ADDRESS injection)."""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

_NS = "jobs"
TERMINAL = ("SUCCEEDED", "FAILED", "STOPPED")


@ray_tpu.remote
class _JobSupervisor:
    """Runs ONE job entrypoint; lives on whichever node scheduled it."""

    def __init__(self, job_id: str, entrypoint: str,
                 gcs_address: Optional[str],
                 packed_env: Optional[dict]) -> None:
        import subprocess
        import threading

        self.job_id = job_id
        self.entrypoint = entrypoint
        self._log_chunks: List[bytes] = []
        self._stopped = False

        env = dict(os.environ)
        if gcs_address:
            env["RAY_TPU_GCS_ADDRESS"] = gcs_address
        cwd = None
        if packed_env:
            # packed by runtime_env.pack on the submitting side:
            # working_dir arrives as an object-store archive, so jobs
            # run with their code on ANY node, like task runtime envs.
            from ray_tpu._private import runtime_env as rte
            from ray_tpu._private.client import get_global_client
            for k, v in (packed_env.get("env_vars") or {}).items():
                env[str(k)] = str(v)
            wd = packed_env.get("working_dir")
            if wd:
                cwd = rte._ensure_extracted(
                    wd, get_global_client().session_dir)
                env["PYTHONPATH"] = (cwd + os.pathsep
                                     + env.get("PYTHONPATH", ""))
        try:
            self.proc = subprocess.Popen(
                entrypoint, shell=True, env=env, cwd=cwd,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        except OSError as e:
            self._log_chunks.append(
                f"job spawn failed: {e!r}\n".encode())
            self._flush_logs()
            self._set_status("FAILED", rc=None)
            raise
        # Status flips to RUNNING only once the process exists — a
        # failed spawn must never leave a phantom RUNNING record.
        self._set_status("RUNNING")
        self._pump = threading.Thread(target=self._pump_loop,
                                      daemon=True, name="rtpu-job-pump")
        self._pump.start()

    # -- state in GCS KV (survives this actor) -------------------------
    def _kv(self):
        from ray_tpu._private.client import get_global_client
        return get_global_client()

    def _set_status(self, status: str, rc: Optional[int] = None) -> None:
        meta = {"job_id": self.job_id, "status": status,
                "entrypoint": getattr(self, "entrypoint", ""),
                "return_code": rc, "update_time": time.time()}
        self._kv().kv_put(_NS, f"{self.job_id}/meta".encode(),
                          json.dumps(meta).encode())

    def _flush_logs(self) -> None:
        self._kv().kv_put(_NS, f"{self.job_id}/logs".encode(),
                          b"".join(self._log_chunks))

    def _pump_loop(self) -> None:
        for line in self.proc.stdout:
            self._log_chunks.append(line)
            if len(self._log_chunks) % 20 == 0:
                self._flush_logs()
        rc = self.proc.wait()
        self._flush_logs()
        if self._stopped:
            self._set_status("STOPPED", rc)
        elif rc == 0:
            self._set_status("SUCCEEDED", rc)
        else:
            self._set_status("FAILED", rc)

    # -- control -------------------------------------------------------
    def stop(self) -> bool:
        self._stopped = True
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
        # Join the log pump (it exits when the child's stdout closes):
        # an unjoined pump racing actor teardown could flush its final
        # log chunk against a closed client (RT014 self-finding).  The
        # terminal status write is the pump's last act, so a joined
        # stop() also guarantees status is final when we return.
        pump = getattr(self, "_pump", None)
        if pump is not None and pump.is_alive():
            pump.join(timeout=10.0)
        return True

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Submit/inspect jobs on a cluster (sdk.py:109)."""

    def __init__(self, address: Optional[str] = None) -> None:
        self._owns_session = False
        if not ray_tpu.is_initialized():
            gcs = None
            if address:
                host, _, port = address.rpartition(":")
                gcs = (host or "127.0.0.1", int(port))
            ray_tpu.init(num_cpus=0, gcs_address=gcs)
            self._owns_session = True
        if address is None:
            # Already-initialized driver: recover the cluster address so
            # job scripts join THIS cluster instead of silently starting
            # their own (node_info carries the node's gcs_address).
            from ray_tpu._private.client import get_global_client
            ga = get_global_client().node_info().get("gcs_address")
            if ga:
                address = f"{ga[0]}:{ga[1]}"
        self.address = address

    # -- API -----------------------------------------------------------
    def submit_job(self, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"job-{uuid.uuid4().hex[:10]}"
        from ray_tpu._private import runtime_env as rte
        packed = rte.pack(runtime_env)
        # An epsilon of CPU keeps the supervisor off resourceless
        # transient client nodes (it must outlive this client).
        sup = _JobSupervisor.options(
            resources={"CPU": 0.001}, lifetime="detached",
            name=f"_job_supervisor:{job_id}",
        ).remote(job_id, entrypoint, self.address, packed)
        # Surface immediate spawn failures (bad cwd etc.) synchronously.
        ray_tpu.get(sup.ping.remote(), timeout=60)
        return job_id

    def _kv(self):
        from ray_tpu._private.client import get_global_client
        return get_global_client()

    def get_job_status(self, job_id: str) -> str:
        raw = self._kv().kv_get(_NS, f"{job_id}/meta".encode())
        if raw is None:
            raise ValueError(f"no such job {job_id!r}")
        return json.loads(raw)["status"]

    def get_job_info(self, job_id: str) -> dict:
        raw = self._kv().kv_get(_NS, f"{job_id}/meta".encode())
        if raw is None:
            raise ValueError(f"no such job {job_id!r}")
        return json.loads(raw)

    def get_job_logs(self, job_id: str) -> str:
        raw = self._kv().kv_get(_NS, f"{job_id}/logs".encode())
        return (raw or b"").decode(errors="replace")

    def list_jobs(self) -> List[dict]:
        out = []
        for key in self._kv().kv_keys(_NS):
            if key.endswith(b"/meta"):
                raw = self._kv().kv_get(_NS, key)
                if raw:
                    out.append(json.loads(raw))
        return sorted(out, key=lambda j: j.get("update_time", 0))

    def wait(self, job_id: str, timeout: float = 300.0,
             poll_s: float = 0.2) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            if status in TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {job_id} still "
                           f"{self.get_job_status(job_id)} "
                           f"after {timeout}s")

    def stop_job(self, job_id: str) -> bool:
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
        except ValueError:
            return False
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def close(self) -> None:
        if self._owns_session:
            ray_tpu.shutdown()
