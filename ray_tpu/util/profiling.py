"""Tracing/profiling: runtime timeline + user spans + TPU profiler.

Reference surface:
* `ray.timeline(filename)` (python/ray/_private/state.py chrome-trace
  export of profile events),
* `ray.util.tracing` span instrumentation — here `span()` /
  `@profiled`, recorded into the same per-node event ring workers feed
  with task execution spans,
* TPU side: `tpu_trace()` wraps `jax.profiler.trace`, producing the
  XLA/TensorBoard profile (the tool that actually explains device time
  — the runtime timeline explains scheduling time).
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.client import get_global_client


def _client():
    c = get_global_client()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized")
    return c


def timeline_events(cluster: bool = True) -> List[dict]:
    """Raw profile events: task execution spans (name/start/end/pid/
    node) + custom `span()` records."""
    return _client().timeline_events(cluster=cluster)


def timeline(filename: Optional[str] = None) -> Any:
    """Chrome-trace export (open in chrome://tracing or Perfetto).
    Returns the event list; writes JSON when `filename` is given.
    Reference: ray.timeline."""
    traced = []
    for ev in timeline_events():
        traced.append({
            "name": ev.get("name", "<span>"),
            "cat": ("actor" if ev.get("actor") else
                    "user" if ev.get("user") else "task"),
            "ph": "X",
            "ts": ev["start"] * 1e6,
            "dur": max(ev["end"] - ev["start"], 0.0) * 1e6,
            "pid": ev.get("node_id", "node")[:8],
            "tid": ev.get("pid", 0),
            "args": {k: v for k, v in ev.items()
                     if k in ("failed", "extra")},
        })
    traced.sort(key=lambda e: e["ts"])
    if filename:
        with open(filename, "w") as f:
            json.dump(traced, f)
    return traced


@contextlib.contextmanager
def span(name: str, **extra):
    """Record a custom span from driver or task code into the runtime
    timeline (reference: ray.util.tracing spans / ray.profile)."""
    t0 = time.time()
    try:
        yield
    finally:
        try:
            _client().profile_event({
                "name": name, "start": t0, "end": time.time(),
                "pid": os.getpid(), "user": True,
                "extra": extra or None})
        except Exception:
            pass


def profiled(fn=None, *, name: Optional[str] = None):
    """Decorator form of `span()`."""
    def deco(f):
        @functools.wraps(f)
        def wrapper(*a, **kw):
            with span(name or f.__qualname__):
                return f(*a, **kw)
        return wrapper
    return deco(fn) if fn is not None else deco


@contextlib.contextmanager
def tpu_trace(logdir: str):
    """XLA device profile via jax.profiler (view in TensorBoard /
    xprof).  This captures MXU utilization, HBM traffic, and fusion
    timing — the device-side complement to the runtime timeline."""
    import jax
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Device-side named region (jax.profiler.TraceAnnotation) so jit
    regions show under `name` in the xprof timeline."""
    import jax
    return jax.profiler.TraceAnnotation(name)
