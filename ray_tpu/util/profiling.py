"""Tracing/profiling: runtime timeline + user spans + TPU profiler.

Reference surface:
* `ray.timeline(filename)` (python/ray/_private/state.py chrome-trace
  export of profile events),
* `ray.util.tracing` span instrumentation — here `span()` /
  `@profiled`, recorded into the same per-node event ring workers feed
  with task execution spans,
* TPU side: `tpu_trace()` wraps `jax.profiler.trace`, producing the
  XLA/TensorBoard profile (the tool that actually explains device time
  — the runtime timeline explains scheduling time).
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import tracing
from ray_tpu._private.client import get_global_client


def _client():
    c = get_global_client()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized")
    return c


def current_trace_id() -> Optional[str]:
    """The ambient trace id (set inside `span()` bodies and task
    executions), or None outside any trace."""
    ctx = tracing.current()
    return ctx["trace_id"] if ctx else None


def timeline_events(cluster: bool = True) -> List[dict]:
    """Raw profile events: task execution spans (name/start/end/pid/
    node) + custom `span()` records."""
    return _client().timeline_events(cluster=cluster)


_TRACE_ARG_KEYS = ("failed", "extra", "trace_id", "span_id",
                   "parent_span_id", "task_id")


def timeline(filename: Optional[str] = None) -> Any:
    """Chrome-trace export (open in chrome://tracing or Perfetto).
    Returns the event list; writes JSON when `filename` is given.

    Task-lifecycle records expand into per-stage child spans
    (submit/queued/dispatch/executing) on the worker's row, linked to
    the proxy/router/user spans of the same request by `trace_id` in
    `args` — one flame per request across processes.
    Reference: ray.timeline."""
    traced = []
    for ev in timeline_events():
        args = {k: v for k, v in ev.items() if k in _TRACE_ARG_KEYS
                and v is not None}
        if ev.get("kind") == "gcs_restart":
            args["epoch"] = ev.get("epoch")
            args["resync_s"] = ev.get("resync_s")
        if ev.get("kind") == "stall":
            # Sentinel capture: elapsed/threshold plus (a bounded
            # slice of) the worker stack ride in the span args.
            args["elapsed_s"] = ev.get("elapsed_s")
            args["threshold_s"] = ev.get("threshold_s")
            stack = ev.get("stack") or ""
            args["stack"] = stack[:4000]
        if ev.get("kind") == "slow_rpc":
            # Slow-RPC sentinel: same shape as a stall capture plus
            # the handler method and a size-bounded args summary.
            args["method"] = ev.get("method")
            args["elapsed_s"] = ev.get("elapsed_s")
            args["threshold_s"] = ev.get("threshold_s")
            args["rpc_args"] = ev.get("rpc_args")
            stack = ev.get("stack") or ""
            args["stack"] = stack[:4000]
        if ev.get("kind") == "sched":
            # Batched scheduler-decision span: outcome counts for the
            # scheduling episode the span covers.
            args["outcomes"] = ev.get("outcomes")
            args["decisions"] = ev.get("decisions")
        row = {
            "name": ev.get("name", "<span>"),
            "cat": ("lifecycle" if ev.get("kind") == "lifecycle" else
                    "drain" if ev.get("kind") == "drain" else
                    "stall" if ev.get("kind") == "stall" else
                    "slow_rpc" if ev.get("kind") == "slow_rpc" else
                    "sched" if ev.get("kind") == "sched" else
                    "gcs_restart" if ev.get("kind") == "gcs_restart"
                    else "actor" if ev.get("actor") else
                    "user" if ev.get("user") else "task"),
            "ph": "X",
            "ts": ev["start"] * 1e6,
            "dur": max(ev["end"] - ev["start"], 0.0) * 1e6,
            "pid": ev.get("node_id", "node")[:8],
            "tid": ev.get("pid", 0),
            "args": args,
        }
        traced.append(row)
        if ev.get("kind") == "lifecycle":
            base = ev.get("task_name") or ev.get("name", "<task>")
            for stage, s0, s1 in tracing.stage_intervals(
                    ev.get("stages") or {}):
                traced.append({
                    "name": f"{base}:{stage}",
                    "cat": "lifecycle",
                    "ph": "X",
                    "ts": s0 * 1e6,
                    "dur": max(s1 - s0, 0.0) * 1e6,
                    "pid": row["pid"],
                    "tid": row["tid"],
                    "args": dict(args, stage=stage),
                })
    traced.sort(key=lambda e: e["ts"])
    if filename:
        with open(filename, "w") as f:
            json.dump(traced, f)
    return traced


def record_span(name: str, start: float, end: float,
                trace_ctx: Optional[Dict[str, str]] = None,
                **extra) -> None:
    """Record a span with explicit timestamps (e.g. a latency
    decomposition measured after the fact).  Attaches the ambient
    trace context — or an explicit `trace_ctx` captured earlier, for
    spans finalized outside the originating context (generator
    drains, callbacks) — so the span joins the request's trace."""
    ev: Dict[str, Any] = {"name": name, "start": start, "end": end,
                          "pid": os.getpid(), "user": True,
                          "extra": extra or None}
    ctx = trace_ctx if trace_ctx is not None else tracing.current()
    if ctx is not None:
        ev["trace_id"] = ctx["trace_id"]
        ev["span_id"] = tracing.new_span_id()
        ev["parent_span_id"] = ctx["span_id"]
    try:
        _client().profile_event(ev)
    except Exception:
        pass


@contextlib.contextmanager
def span(name: str, **extra):
    """Record a custom span from driver or task code into the runtime
    timeline (reference: ray.util.tracing spans / ray.profile).

    Opens a child of the ambient trace context (or roots a new trace),
    and activates it for the body — so tasks submitted inside the span
    carry the trace across processes."""
    info = tracing.child_span()
    token = tracing.set_current(info)
    t0 = time.time()
    try:
        yield
    finally:
        tracing.reset(token)
        try:
            _client().profile_event({
                "name": name, "start": t0, "end": time.time(),
                "pid": os.getpid(), "user": True,
                "trace_id": info["trace_id"],
                "span_id": info["span_id"],
                "parent_span_id": info["parent_span_id"],
                "extra": extra or None})
        except Exception:
            pass


def profiled(fn=None, *, name: Optional[str] = None):
    """Decorator form of `span()`."""
    def deco(f):
        @functools.wraps(f)
        def wrapper(*a, **kw):
            with span(name or f.__qualname__):
                return f(*a, **kw)
        return wrapper
    return deco(fn) if fn is not None else deco


@contextlib.contextmanager
def tpu_trace(logdir: str):
    """XLA device profile via jax.profiler (view in TensorBoard /
    xprof).  This captures MXU utilization, HBM traffic, and fusion
    timing — the device-side complement to the runtime timeline."""
    import jax
    with jax.profiler.trace(logdir):
        yield


def annotate(name: str):
    """Device-side named region (jax.profiler.TraceAnnotation) so jit
    regions show under `name` in the xprof timeline."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def export_otlp(filename: Optional[str] = None,
                endpoint: Optional[str] = None,
                service_name: str = "ray_tpu") -> dict:
    """Export the profile spans as OTLP/JSON (the OpenTelemetry
    ExportTraceServiceRequest schema), so any OTLP-ingesting backend
    (Jaeger, Tempo, collector) can read them — the reference's
    util/tracing/tracing_helper.py role without requiring the otel SDK
    in the image.  Writes to `filename` and/or POSTs to `endpoint`
    (an OTLP/HTTP traces URL); returns the payload."""
    import os
    import urllib.request

    def span_id(n: int) -> str:
        return f"{n & 0xFFFFFFFFFFFFFFFF:016x}"

    spans = []
    # Fallback trace for legacy events recorded without a trace
    # context; traced events carry their own per-request trace ids.
    trace_id = os.urandom(16).hex()
    for i, ev in enumerate(timeline_events()):
        attrs = [{"key": "node.id",
                  "value": {"stringValue": str(ev.get("node_id", ""))[:16]}},
                 {"key": "process.pid",
                  "value": {"intValue": str(ev.get("pid", 0))}}]
        for k, v in (ev.get("extra") or {}).items() \
                if isinstance(ev.get("extra"), dict) else []:
            attrs.append({"key": str(k),
                          "value": {"stringValue": str(v)}})
        sp = {
            "traceId": ev.get("trace_id") or trace_id,
            "spanId": ev.get("span_id") or span_id(i + 1),
            "name": ev.get("name", "<span>"),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(int(ev["start"] * 1e9)),
            "endTimeUnixNano": str(int(max(ev["end"], ev["start"]) * 1e9)),
            "attributes": attrs,
            "status": ({"code": 2} if ev.get("failed")
                       else {"code": 1}),
        }
        if ev.get("parent_span_id"):
            sp["parentSpanId"] = ev["parent_span_id"]
        spans.append(sp)
    payload = {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": service_name}}]},
        "scopeSpans": [{
            "scope": {"name": "ray_tpu.profiling"},
            "spans": spans,
        }],
    }]}
    if filename:
        with open(filename, "w") as f:
            json.dump(payload, f)
    if endpoint:
        req = urllib.request.Request(
            endpoint, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).read()
    return payload


def stack_traces(timeout: float = 10.0,
                 cluster: bool = True) -> Dict[Any, str]:
    """On-demand stack dump of every live worker process in the
    cluster (reference: the dashboard reporter's py-spy integration).
    Returns {pid: formatted stacks}; workers on remote nodes appear
    under "pid@node" keys (pids collide across hosts).  cluster=False
    restricts to the local node — which used to be the silent behavior
    of this documented "every live worker" API."""
    return _client().conn.call({"type": "stack_dump",
                                "timeout": timeout,
                                "cluster": cluster},
                               timeout=timeout + 15.0)["stacks"]


def stack_task(task_id: str, timeout: float = 10.0) -> Dict[Any, str]:
    """Targeted stack capture of the worker(s) currently executing the
    task whose id matches the hex prefix `task_id` (anywhere in the
    cluster) — the on-demand face of the stall sentinel's automatic
    captures.  Returns {} when the task is not executing."""
    return _client().conn.call({"type": "stack_dump",
                                "timeout": timeout,
                                "task_id": task_id,
                                "cluster": True},
                               timeout=timeout + 15.0)["stacks"]


def folded_stacks(samples: int = 40, interval_s: float = 0.02,
                  timeout: float = 10.0, cluster: bool = True,
                  task_id: Optional[str] = None) -> Dict[str, int]:
    """Cluster flamegraph sampling: every live worker captures its
    thread stacks `samples` times, `interval_s` apart; the node layer
    merges the folded-stack counts across workers and nodes.  With a
    `task_id` hex prefix, only the worker(s) executing that task are
    sampled.  Returns {"thread;frame;frame;...": count}."""
    msg = {"type": "stack_dump", "timeout": timeout,
           "cluster": cluster, "samples": samples,
           "interval_s": interval_s}
    if task_id:
        msg["task_id"] = task_id
    reply = _client().conn.call(
        msg, timeout=timeout + samples * interval_s + 15.0)
    return reply.get("folded") or {}


def flamegraph(samples: int = 40, interval_s: float = 0.02,
               timeout: float = 10.0, cluster: bool = True,
               task_id: Optional[str] = None,
               filename: Optional[str] = None) -> str:
    """`folded_stacks()` rendered in the flamegraph.pl folded format
    (one "stack count" line per distinct stack) — pipe the output into
    flamegraph.pl / speedscope, or read hot frames straight off the
    counts.  Writes to `filename` when given; returns the text."""
    folded = folded_stacks(samples=samples, interval_s=interval_s,
                           timeout=timeout, cluster=cluster,
                           task_id=task_id)
    text = "\n".join(f"{stack} {count}" for stack, count in
                     sorted(folded.items(),
                            key=lambda kv: (-kv[1], kv[0])))
    if filename:
        with open(filename, "w") as f:
            f.write(text + ("\n" if text else ""))
    return text
