"""Distributed queue (reference: python/ray/util/queue.py — an
actor-backed Queue with put/get/qsize/empty/full and blocking
semantics).

The backing actor is ASYNC: puts and gets park on an asyncio.Queue
inside the actor's event loop, so blocking calls cost no polling
anywhere — a get on an empty queue simply leaves its actor call
pending until a put lands (the actor runs with max_concurrency so
parked gets never block puts).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int) -> None:
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item: Any,
                  timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None) -> tuple:
        if timeout is None:
            return (True, await self._q.get())
        try:
            return (True, await asyncio.wait_for(self._q.get(),
                                                 timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def put_nowait(self, item: Any) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self) -> tuple:
        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    async def qsize(self) -> int:
        return self._q.qsize()

    async def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    """Client handle; safe to pass to tasks/actors (pickles to the
    same backing actor)."""

    def __init__(self, maxsize: int = 0, *,
                 _actor: Optional[Any] = None) -> None:
        if _actor is not None:
            self._actor = _actor
            return
        cls = ray_tpu.remote(_QueueActor)
        self._actor = cls.options(max_concurrency=64).remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            ok = ray_tpu.get(self._actor.put_nowait.remote(item))
            if not ok:
                raise Full("queue is full")
            return
        ok = ray_tpu.get(self._actor.put.remote(item, timeout))
        if not ok:
            raise Full(f"put timed out after {timeout}s")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty(f"get timed out after {timeout}s")
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        for it in items:
            self.put(it, block=False)

    def get_nowait_batch(self, n: int) -> List[Any]:
        return [self.get(block=False) for _ in range(n)]

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        ms = ray_tpu.get(self._actor.maxsize.remote())
        return bool(ms) and self.qsize() >= ms

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)

    def __reduce__(self):
        return (Queue, (0,), {"_actor": self._actor})

    def __setstate__(self, state):
        self._actor = state["_actor"]
