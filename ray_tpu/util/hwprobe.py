"""Resilient TPU-backend probing for the perf-evidence pipeline.

Two consecutive rounds of end-of-round bench captures died with rc=1
because ``jax.devices()`` was called directly on a wedged axon tunnel
(``BENCH_r03.json`` / ``BENCH_r04.json``: "Unable to initialize backend
'axon'").  JAX caches a failed backend init for the life of the
process, so retrying in-process is useless; the probe therefore runs in
a *subprocess* and the caller only imports jax once a probe succeeds.

Mirrors the reference's release-log discipline
(reference ``release/release_logs/<version>/``): every successful
hardware capture is also recorded under ``release_logs/last_good/`` so
a failed capture can emit the last-good number with provenance instead
of dying with a traceback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional

_PROBE_SRC = (
    "import json, jax\n"
    "d = jax.devices()[0]\n"
    "print(json.dumps({'platform': d.platform,"
    " 'device_kind': getattr(d, 'device_kind', d.platform),"
    " 'n_devices': jax.device_count()}))\n"
)


def probe(timeout_s: float = 90.0) -> Dict[str, Any]:
    """One subprocess probe of the JAX backend.

    Returns ``{"ok": True, "platform": ..., "device_kind": ...}`` or
    ``{"ok": False, "error": <last line of stderr / 'timeout'>}``.
    The parent process never touches jax, so a wedged tunnel cannot
    poison its backend cache.
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"probe timeout after {timeout_s:.0f}s"}
    if out.returncode == 0 and out.stdout.strip():
        try:
            info = json.loads(out.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            return {"ok": False, "error": f"unparseable probe: {out.stdout[-200:]}"}
        return {"ok": True, **info}
    err_lines = [l for l in out.stderr.strip().splitlines() if l.strip()]
    return {"ok": False, "error": err_lines[-1] if err_lines else f"rc={out.returncode}"}


def wait_for_backend(attempts: Optional[int] = None,
                     probe_timeout_s: Optional[float] = None,
                     delays: Optional[list] = None) -> Dict[str, Any]:
    """Bounded retry with backoff around backend init.

    Defaults: 5 attempts, worst case ~13 minutes (5 x 90 s probe
    timeouts + 20/45/90/180 s sleeps between them).  Env overrides
    ``HW_PROBE_ATTEMPTS`` / ``HW_PROBE_TIMEOUT_S`` let the driver
    tighten or extend the window.  Returns the last probe result, plus
    ``attempts``/``elapsed_s`` and the per-attempt error log on failure.
    """
    # Explicitly CPU-pinned runs (tests, smoke) need no tunnel probe —
    # a subprocess jax import costs ~30 s on a loaded 1-vCPU host.
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # axon's sitecustomize hook can re-pin the live jax config to
        # its tunneled platform regardless of the env var, and a wedged
        # tunnel then hangs the CPU run at first backend touch.  Same
        # two-part defense as tests/conftest.py: drop the pool AND
        # force the live config back to cpu (jax is typically already
        # imported by the sitecustomize at this point).
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        return {"ok": True, "platform": "cpu", "device_kind": "cpu",
                "n_devices": None, "attempts": 0, "elapsed_s": 0.0,
                "skipped_probe": True}
    if attempts is None:
        attempts = int(os.environ.get("HW_PROBE_ATTEMPTS", "5"))
    if probe_timeout_s is None:
        probe_timeout_s = float(os.environ.get("HW_PROBE_TIMEOUT_S", "90"))
    delays = delays if delays is not None else [20, 45, 90, 180]
    t0 = time.time()
    log = []
    for i in range(attempts):
        r = probe(probe_timeout_s)
        if r["ok"]:
            r["attempts"] = i + 1
            r["elapsed_s"] = round(time.time() - t0, 1)
            return r
        log.append(r["error"])
        if i < attempts - 1:
            time.sleep(delays[min(i, len(delays) - 1)])
    return {"ok": False, "attempts": attempts,
            "elapsed_s": round(time.time() - t0, 1),
            "error": (f"backend unavailable after {attempts} attempts over "
                      f"{(time.time() - t0) / 60:.1f} min"),
            "attempt_errors": log}


def lg_name(prefix: str, model: str, default_model: str) -> str:
    """Canonical release_logs/last_good record name for a bench config
    (shared by bench.py and serve_bench.py so the naming scheme can
    never drift between them and orphan a last-good history)."""
    if model == default_model:
        return prefix
    return f"{prefix}_{model.replace('-', '')}"


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def record_last_good(name: str, payload: Dict[str, Any]) -> None:
    """Persist a successful hardware capture under release_logs/."""
    d = os.path.join(repo_root(), "release_logs", "last_good")
    os.makedirs(d, exist_ok=True)
    rec = dict(payload)
    rec["_captured_unix"] = int(time.time())
    with open(os.path.join(d, f"{name}.json"), "w") as f:
        json.dump(rec, f, indent=1)


def load_last_good(name: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(repo_root(), "release_logs", "last_good", f"{name}.json")
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (ValueError, OSError):
        return None


def stale_record(name: str, failure: Dict[str, Any],
                 provenance_hint: str) -> Dict[str, Any]:
    """Build the structured failure line bench emits when the backend
    never comes up: the last-good number, marked stale, plus the
    failure diagnostics — never a bare traceback."""
    last = load_last_good(name)
    out: Dict[str, Any] = {
        "stale": True,
        "backend_error": failure.get("error"),
        "probe_attempts": failure.get("attempts"),
        "probe_elapsed_s": failure.get("elapsed_s"),
    }
    if last is not None:
        out.update({k: v for k, v in last.items() if not k.startswith("_")})
        out["stale"] = True
        out["provenance"] = (
            f"last-good hardware capture (release_logs/last_good/{name}.json,"
            f" unix {last.get('_captured_unix')}); {provenance_hint}")
    else:
        out.update({"metric": name, "value": None, "unit": "unavailable",
                    "vs_baseline": None,
                    "provenance": f"no last-good record; {provenance_hint}"})
    return out


def ensure_backend(lg_name: str, hint: str) -> Dict[str, Any]:
    """Shared bench entry: wait for the backend or emit-stale-and-exit.

    On success returns the probe info.  On failure prints the one JSON
    line the driver expects (last-good number marked stale, with the
    probe diagnostics) and exits 0 — the capture is never a bare
    traceback again.
    """
    pr = wait_for_backend()
    if not pr["ok"]:
        print(json.dumps(stale_record(lg_name, pr, hint)))
        sys.exit(0)
    return pr
