"""Python-side proxies for NATIVE (C++) worker functions and actors.

Reference analog: calling C++ tasks/actors from Python
(python/ray/cross_language.py `ray.cross_language.cpp_function` /
`cpp_actor_class`).  The C++ side registers names via
cpp/ray_tpu_worker.hpp; these proxies submit against those names with
plain-value args and return ordinary ObjectRefs — `ray_tpu.get`
works unchanged, and native failures surface as typed errors.

    from ray_tpu.util import native
    add = native.cpp_function("vec_add")
    ref = add.remote([1, 2], [3, 4])            # -> ObjectRef
    counter = native.cpp_actor("Counter").remote(10)
    counter.add.remote(5)
"""

from __future__ import annotations

from typing import Any, List

import ray_tpu
from ray_tpu._private.node_native import _check_plain
from ray_tpu.object_ref import ObjectRef


def _submit(payload: dict) -> dict:
    client = ray_tpu._ensure_connected()
    for a in payload.get("args", ()):
        _check_plain(a)
    return client.conn.call(payload, timeout=30.0)


def list_native() -> dict:
    """Registered native functions/actor classes on this node."""
    client = ray_tpu._ensure_connected()
    return client.conn.call({"type": "list_native"}, timeout=15.0)


class NativeFunction:
    def __init__(self, name: str) -> None:
        self._name = name

    def remote(self, *args: Any) -> ObjectRef:
        reply = _submit({"type": "submit_native", "kind": "fn",
                         "name": self._name, "args": list(args)})
        return ObjectRef(reply["return_id"], owned=True)


def cpp_function(name: str) -> NativeFunction:
    return NativeFunction(name)


class _NativeMethod:
    def __init__(self, handle: "NativeActorHandle",
                 method: str) -> None:
        self._handle = handle
        self._method = method

    def remote(self, *args: Any) -> ObjectRef:
        reply = _submit({"type": "submit_native",
                         "kind": "actor_method",
                         "instance": self._handle._instance,
                         "method": self._method,
                         "args": list(args)})
        return ObjectRef(reply["return_id"], owned=True)


class NativeActorHandle:
    def __init__(self, instance: bytes, create_ref: ObjectRef) -> None:
        self._instance = instance
        # The constructor's return object: get() it to surface init
        # errors (mirrors Python actor creation semantics).
        self.ready_ref = create_ref

    def kill(self) -> bool:
        """Release the instance's state in the worker (the native
        analog of ray_tpu.kill on an actor handle)."""
        client = ray_tpu._ensure_connected()
        return client.conn.call(
            {"type": "kill_native_actor", "instance": self._instance},
            timeout=15.0)["ok"]

    def __getattr__(self, name: str) -> _NativeMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _NativeMethod(self, name)


class NativeActorClass:
    def __init__(self, class_name: str) -> None:
        self._class_name = class_name

    def remote(self, *args: Any) -> NativeActorHandle:
        reply = _submit({"type": "submit_native",
                         "kind": "actor_create",
                         "name": self._class_name,
                         "args": list(args)})
        return NativeActorHandle(
            reply["instance"],
            ObjectRef(reply["return_id"], owned=True))


def cpp_actor(class_name: str) -> NativeActorClass:
    return NativeActorClass(class_name)
