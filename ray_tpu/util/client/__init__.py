"""Thin client: drive a remote cluster without joining it as a node.

Reference surface: python/ray/util/client/ (ray.init("ray://host:port")
proxying the full API through a gRPC server).  Here the transport is
the node's existing TCP control endpoint — the thin client speaks the
SAME protocol as in-node drivers, minus the shared-memory fast path
(see RemoteCoreClient): puts ship inline, big results pull through the
object-transfer endpoints.

    from ray_tpu.util import client
    client.connect("10.0.0.5:41234")     # node client_address
    # ... the whole ray_tpu.* API now routes to the remote cluster ...
    client.disconnect()

The head's client address is printed by `python -m ray_tpu start
--head` (and available from any node via CoreClient.node_info()).
"""

from __future__ import annotations

from typing import Optional

import ray_tpu
from ray_tpu._private.client import (RemoteCoreClient, get_global_client,
                                     set_global_client)


class ClientContext:
    def __init__(self, client: RemoteCoreClient, address: str) -> None:
        self.client = client
        self.address = address

    def disconnect(self) -> None:
        disconnect()

    def __enter__(self) -> "ClientContext":
        return self

    def __exit__(self, *a) -> None:
        self.disconnect()


def connect(address: str) -> ClientContext:
    """Attach this process to a remote cluster node's control endpoint;
    the global ray_tpu API then routes through it."""
    if ray_tpu.is_initialized():
        raise RuntimeError(
            "ray_tpu is already initialized in this process; "
            "thin-client connect() requires a fresh process "
            "(or call ray_tpu.shutdown() first)")
    host, _, port = address.rpartition(":")
    client = RemoteCoreClient(host or "127.0.0.1", int(port))
    set_global_client(client)
    ray_tpu._mark_worker_connected(client)   # adopt as the session
    ray_tpu._session.is_worker = False
    return ClientContext(client, address)


def disconnect() -> None:
    client = get_global_client()
    if client is None:
        return
    set_global_client(None)
    ray_tpu._session = None
    try:
        client.close()
    except Exception:
        pass


def is_connected() -> bool:
    return isinstance(get_global_client(), RemoteCoreClient)
