"""Placement groups: gang-reserved resource bundles across the cluster.

Analog of the reference's python/ray/util/placement_group.py:41
(`PlacementGroup`, `placement_group` at :145, `remove_placement_group`)
with the GCS-side 2PC reserve/commit of
src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h:283 implemented
in the node service (`_pg_create_loop` / `_pg_try_commit`).

TPU-native extension: `tpu_slice_bundles` builds STRICT_SPREAD bundles
for a whole TPU slice — one bundle per host, each carrying the host's
chips, the head bundle also carrying the `TPU-{type}-head` marker the
reference's TPU accelerator support schedules multi-host slices with
(python/ray/_private/accelerators/tpu.py:360-362).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ray_tpu.object_ref import ObjectRef

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    """Handle to a (possibly still-materializing) placement group."""

    def __init__(self, id: bytes, bundle_specs: List[Dict[str, float]],
                 ready_oid: bytes) -> None:
        self.id = id
        self.bundle_specs = list(bundle_specs)
        self._ready_oid = ready_oid

    def _check_bundle_index(self, index: int) -> None:
        if not 0 <= index < len(self.bundle_specs):
            raise ValueError(
                f"placement_group_bundle_index {index} out of range for "
                f"a {len(self.bundle_specs)}-bundle placement group")

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self) -> ObjectRef:
        """ObjectRef that resolves (to True) once every bundle is
        reserved — await with ray_tpu.get(pg.ready())."""
        return ObjectRef._from_wire(self._ready_oid)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        import ray_tpu
        try:
            ray_tpu.get(self.ready(), timeout=timeout_seconds)
            return True
        except ray_tpu.exceptions.GetTimeoutError:
            return False

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs,
                                 self._ready_oid))

    def __repr__(self) -> str:
        return (f"PlacementGroup({self.id.hex()[:12]}, "
                f"{len(self.bundle_specs)} bundles)")


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: Optional[str] = None) -> PlacementGroup:
    """Reserve a gang of resource bundles (2PC across nodes).

    Returns immediately; use pg.ready()/pg.wait() to await placement.
    """
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, "
                         f"got {strategy!r}")
    if not bundles or not all(isinstance(b, dict) and b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    for b in bundles:
        if any(v <= 0 for v in b.values()):
            raise ValueError(f"bundle resource amounts must be > 0: {b}")
    import ray_tpu
    client = ray_tpu._ensure_connected()
    pg_id = os.urandom(16)
    ready_oid = os.urandom(16)
    client.create_pg(pg_id, [dict(b) for b in bundles], strategy, name,
                     ready_oid)
    return PlacementGroup(pg_id, bundles, ready_oid)


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release all of a placement group's bundles back to their nodes."""
    import ray_tpu
    ray_tpu._ensure_connected().remove_pg(pg.id)


def placement_group_table(pg: PlacementGroup) -> dict:
    """State of one placement group: {'state', 'nodes'}."""
    import ray_tpu
    return ray_tpu._ensure_connected().pg_state(pg.id)


def tpu_slice_bundles(accelerator_type: str, num_hosts: int,
                      chips_per_host: int = 4) -> List[Dict[str, float]]:
    """Bundles for gang-scheduling one whole TPU slice: one bundle per
    host; bundle 0 additionally claims the slice-head marker resource so
    exactly one gang lands per slice."""
    bundles: List[Dict[str, float]] = []
    for h in range(num_hosts):
        b: Dict[str, float] = {"TPU": float(chips_per_host)}
        if h == 0:
            b[f"TPU-{accelerator_type}-head"] = 1.0
        bundles.append(b)
    return bundles
