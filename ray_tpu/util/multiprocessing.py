"""`multiprocessing.Pool` drop-in over the task runtime.

Reference surface: python/ray/util/multiprocessing/pool.py (Pool with
map/starmap/imap/imap_unordered/apply(_async), chunking, context
manager).  Each chunk is one remote task, so pools span the whole
cluster instead of one machine."""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import ray_tpu


@ray_tpu.remote
def _run_chunk(fn: Callable, chunk: List[tuple], star: bool) -> List[Any]:
    if star:
        return [fn(*args) for args in chunk]
    return [fn(arg) for (arg,) in chunk]


@ray_tpu.remote
def _apply_one(fn: Callable, args: tuple, kwds: dict) -> Any:
    return fn(*args, **kwds)


class AsyncResult:
    def __init__(self, refs: List, chunked: bool = True,
                 single: bool = False,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None) -> None:
        self._refs = refs
        self._chunked = chunked
        self._single = single
        if callback is not None or error_callback is not None:
            def waiter():
                try:
                    value = self.get()
                except BaseException as e:  # noqa: BLE001
                    if error_callback is not None:
                        error_callback(e)   # stdlib Pool semantics
                    return
                if callback is not None:
                    callback(value)

            threading.Thread(target=waiter, daemon=True,
                             name="rtpu-pool-callback").start()

    def get(self, timeout: Optional[float] = None) -> Any:
        parts = ray_tpu.get(self._refs, timeout=timeout)
        if self._single:
            return parts[0]
        if not self._chunked:
            return parts
        return [x for part in parts for x in part]

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)


class Pool:
    """Cluster-wide process pool (reference: util/multiprocessing)."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()) -> None:
        if initializer is not None:
            raise NotImplementedError(
                "Pool(initializer=...) is not supported: tasks are "
                "stateless; use an actor for per-worker state")
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cpus = ray_tpu.cluster_resources().get("CPU", 1)
        self._processes = processes or max(int(cpus), 1)
        self._closed = False

    # -- helpers -------------------------------------------------------
    def _chunks(self, iterables: Sequence[Iterable],
                chunksize: Optional[int]) -> List[List[tuple]]:
        items = list(zip(*iterables)) if len(iterables) > 1 \
            else [(x,) for x in iterables[0]]
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool is closed")

    # -- API -----------------------------------------------------------
    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        refs = [_run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks([iterable], chunksize)]
        return AsyncResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_open()
        items = list(iterable)
        if not items:
            return []
        chunks = self._chunks([items], chunksize)
        star_chunks = [[args for (args,) in chunk] for chunk in chunks]
        refs = [_run_chunk.remote(fn, [tuple(a) for a in chunk], True)
                for chunk in star_chunks]
        return AsyncResult(refs).get()

    def apply(self, fn: Callable, args: tuple = (),
              kwds: Optional[dict] = None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None
                    ) -> AsyncResult:
        """`callback` support matches stdlib/joblib expectations."""
        self._check_open()
        kwds = kwds or {}
        ref = _apply_one.remote(fn, args, kwds)
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        # Submit EAGERLY (stdlib semantics: work starts at call time
        # and creating the iterator before close() is legal).
        self._check_open()
        refs = [_run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks([iterable], chunksize)]

        def gen():
            for ref in refs:                   # submission order
                yield from ray_tpu.get(ref)
        return gen()

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        self._check_open()
        refs = [_run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks([iterable], chunksize)]

        def gen():
            pending = list(refs)
            while pending:
                done, pending = ray_tpu.wait(pending, num_returns=1)
                yield from ray_tpu.get(done[0])
        return gen()

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("join() before close()")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *a) -> None:
        self.close()
