"""Cross-language function export (reference:
python/ray/cross_language.py).

The C++ client (cpp/ray_tpu_client.hpp) cannot ship cloudpickled
closures, so cross-language callables are EXPORTED by name from
Python: `export_function("add", add)` registers the function body in
the GCS function table and publishes its function id under the name in
the "cross_lang" KV namespace.  Any native client then submits tasks
against the name with plain-value arguments (ints/floats/strings/
bytes/lists) and reads back a plain-value result — the same
plain-value contract the reference's msgpack-based cross-language
boundary enforces.
"""

from __future__ import annotations

import ray_tpu
from ray_tpu.remote_function import RemoteFunction

_NS = "cross_lang"


def export_function(name: str, fn) -> bytes:
    """Publish a @ray_tpu.remote function for native-client callers;
    returns its function id."""
    if not isinstance(fn, RemoteFunction):
        fn = ray_tpu.remote(fn)
    client = ray_tpu._ensure_connected()
    fid = fn._ensure_registered(client)
    client.kv_put(_NS, name.encode(), fid)
    return fid


def unexport_function(name: str) -> bool:
    client = ray_tpu._ensure_connected()
    return client.kv_del(_NS, name.encode())
