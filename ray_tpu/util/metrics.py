"""Application metrics: Counter / Gauge / Histogram + Prometheus export.

Reference surface: python/ray/util/metrics.py (Counter :115, Gauge :188,
Histogram :263, tag_keys/default_tags semantics) backed by
_private/metrics_agent.py aggregation.

Here every process (driver or worker) keeps a local registry; a daemon
flusher batches deltas to the node service over the existing UDS
connection every `flush_interval_s`, where they aggregate across
processes.  `scrape()` reads the merged series; `prometheus_text()`
renders the standard exposition format (what the reference's agent
serves on its metrics port)."""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.client import get_global_client
from ray_tpu.devtools import leaksan

FLUSH_INTERVAL_S = 1.0

# Prometheus metric-name grammar (exposition format spec).
METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")

# Task-lifecycle stage histogram, auto-recorded by the node service
# for every completed task (stage tag: submit/queued/deps_fetch/
# dispatch/executing/total) — scheduling delay and queue wait land in
# every Prometheus scrape with no user code.
TASK_STAGE_METRIC = "ray_tpu_task_stage_duration_seconds"
TASK_STAGE_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                      1.0, 5.0, 30.0)

# Retry/fault counters, auto-registered node-side like the stage
# histograms (no user code needed for a Prometheus scrape to show
# them).  Tags: reason = worker_crash | node_death | app_error |
# actor_restart | serve_failover; kind = the injected fault kind.
TASK_RETRIES_METRIC = "ray_tpu_task_retries_total"
ACTOR_RESTARTS_METRIC = "ray_tpu_actor_restarts_total"
CHAOS_INJECTED_METRIC = "ray_tpu_chaos_injected_total"

# Graceful node drain (operator drain / TPU preemption notice),
# auto-recorded node-side.  drains_total tags: reason = gcs | sigterm |
# preemption | chaos_preempt.  duration observes the whole drain
# sequence (handback + actor migration + re-replication + quiesce);
# objects_replicated counts sole-holder copies proactively moved to
# healthy peers before the node exits.
NODE_DRAINS_METRIC = "ray_tpu_node_drains_total"
DRAIN_DURATION_METRIC = "ray_tpu_drain_duration_seconds"
DRAIN_OBJECTS_REPLICATED_METRIC = "ray_tpu_drain_objects_replicated_total"
DRAIN_DURATION_BUCKETS = (0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 300.0)

# Memory-and-stall observability plane, auto-recorded node-side.
# object_store_bytes tags: kind = owned | borrowed | pinned_by_actor |
# spilled | drain_replica (per-node object-directory breakdown behind
# `ray_tpu memory` / state.memory_summary()).  task_stalls counts
# executing tasks the stall sentinel flagged (each also gets a `stall`
# lifecycle event carrying the worker's captured stack).
# events_dropped counts lifecycle/profile events evicted from the
# bounded per-node event ring (capacity: event_ring_capacity config).
OBJECT_STORE_BYTES_METRIC = "ray_tpu_object_store_bytes"
TASK_STALLS_METRIC = "ray_tpu_task_stalls_total"
EVENTS_DROPPED_METRIC = "ray_tpu_events_dropped_total"

# Control-plane fault tolerance (GCS kill -9 survivability),
# auto-recorded node-side.  restarts counts recovery-epoch bumps a
# node observed (one per node per GCS restart); reconnects counts
# successful GcsClient re-dials (outages without a restart count
# too); wal_bytes is the GCS write-ahead-log size gauge (from the
# periodic gcs_status poll — watch it saw-tooth with compaction);
# resync_seconds observes the node's bulk state re-publication after
# a reconnect.
GCS_RESTARTS_METRIC = "ray_tpu_gcs_restarts_total"
GCS_RECONNECTS_METRIC = "ray_tpu_gcs_reconnects_total"
GCS_WAL_BYTES_METRIC = "ray_tpu_gcs_wal_bytes"
GCS_RESYNC_SECONDS_METRIC = "ray_tpu_gcs_resync_seconds"
GCS_RESYNC_BUCKETS = (0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)

# Compiled-graph (ray_tpu.dag) fast lane, auto-recorded.  hop_seconds
# tags: edge = local (same-node mmap ring / in-process write) | remote
# (cross-node streamed transfer-plane edge, send->ack round trip).
# executions_total counts CompiledDAG.execute() calls driver-side.
# Bucket floor is 10 µs: the whole point of compiled graphs is hops
# two orders of magnitude below the task path's buckets.
DAG_HOP_SECONDS_METRIC = "ray_tpu_dag_hop_seconds"
DAG_EXECUTIONS_METRIC = "ray_tpu_dag_executions_total"
DAG_HOP_BUCKETS = (0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005,
                   0.01, 0.05, 0.25, 1.0)

# Paged-KV LLM serving plane (serve/llm.py PagedBatcher), recorded by
# the engine thread.  kv_blocks tags: state = used (refcount > 0) |
# cached (refcount 0, retained in the prefix radix tree) | free, plus
# a per-engine tag (the node-side gauge merge is last-write-wins per
# tagset; sum over engines per state for totals — engines zero their
# series on clean stop).
# prefix_cache hits/queries count admission-time radix lookups (hit =
# at least one full block reused); evictions counts cached blocks
# LRU-reclaimed back to the free pool under allocation pressure.
KV_BLOCKS_METRIC = "ray_tpu_kv_blocks"
PREFIX_CACHE_HITS_METRIC = "ray_tpu_prefix_cache_hits_total"
PREFIX_CACHE_QUERIES_METRIC = "ray_tpu_prefix_cache_queries_total"
KV_EVICTIONS_METRIC = "ray_tpu_kv_evictions_total"

# Serve overload-robustness plane (serve/_controller.py autoscaler +
# serve/_admission.py admission control).  requests_shed counts
# requests rejected at admission instead of queued to timeout, tagged
# by deployment and reason (overloaded = token bucket empty,
# queue_full = queue-depth cap for the request's priority class,
# tenant_quota = per-tenant fair-share exceeded under pressure).
# replicas is the controller's per-deployment replica gauge by state
# (running | draining | target); queue_depth is the autoscaler's last
# polled total outstanding requests per deployment.
SERVE_REQUESTS_SHED_METRIC = "ray_tpu_serve_requests_shed_total"
SERVE_REPLICAS_METRIC = "ray_tpu_serve_replicas"
SERVE_QUEUE_DEPTH_METRIC = "ray_tpu_serve_queue_depth"

# Training telemetry & goodput plane (train/telemetry.py), recorded
# by train-session workers.  step_seconds tags: phase = data_wait
# (blocked on the next batch — the ingest-vs-compute signal) |
# compile (jit cache miss steps: tracing/lowering) | step (device
# compute) | checkpoint | sync | idle (unattributed host time).
# mfu / tokens_per_second are per-run gauges over a decayed window
# (rank 0 reports; removed on telemetry stop — the RT015 contract).
# goodput_fraction tags (run, class): the run-level wall-clock ledger
# classes productive | compile | input_wait | checkpoint | sync |
# restart_recovery | idle as fractions of wall.  stragglers_total
# counts gang workers the reducer flagged (one targeted stack capture
# each, via the stall-sentinel dump path).
TRAIN_STEP_SECONDS_METRIC = "ray_tpu_train_step_seconds"
TRAIN_STEP_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                      30.0, 120.0)
TRAIN_MFU_METRIC = "ray_tpu_train_mfu"
TRAIN_TOKENS_PER_S_METRIC = "ray_tpu_train_tokens_per_second"
TRAIN_GOODPUT_FRACTION_METRIC = "ray_tpu_train_goodput_fraction"
TRAIN_STRAGGLERS_METRIC = "ray_tpu_train_stragglers_total"
# Elastic gang resize (train/elastic.py): resizes_total counts gang
# resizes tagged direction = shrink | grow; world_size is a per-run
# gauge of the CURRENT gang size (removed when the run finalizes —
# the RT015 dead-writer contract, like the other per-run train
# gauges).  Resize dead time lands in the goodput ledger's
# resize_recovery class, distinct from restart_recovery.
TRAIN_RESIZES_METRIC = "ray_tpu_train_resizes_total"
TRAIN_WORLD_SIZE_METRIC = "ray_tpu_train_world_size"

# Concurrency sanitizer (devtools/locksan.py, enabled with
# RAY_TPU_LOCKSAN=1).  wait_seconds observes how long acquire()
# blocked on instrumented locks (untagged: one distribution per
# process; per-site detail lives in the locksan report);
# contention_total counts acquires that found the lock held, tagged
# by the lock's creation site (file:line).
LOCK_WAIT_SECONDS_METRIC = "ray_tpu_lock_wait_seconds"
LOCK_CONTENTION_METRIC = "ray_tpu_lock_contention_total"
LOCK_WAIT_BUCKETS = (0.00001, 0.0001, 0.001, 0.01, 0.05, 0.25, 1.0,
                     5.0)

# Resource-lifecycle sanitizer (devtools/leaksan.py, enabled with
# RAY_TPU_LEAKSAN=1).  resources_live gauges the ledger's live count
# per kind (kv_block | admission_slot | spill_fd | channel_mmap |
# thread | metric_series); resource_leaks counts leaks the ledger
# positively detected — a resource still live when its process dumped
# at exit, or a release fired twice (the exactly-once contract cuts
# both ways).
RESOURCES_LIVE_METRIC = "ray_tpu_resources_live"
RESOURCE_LEAKS_METRIC = "ray_tpu_resource_leaks_total"

# XLA-compilation sanitizer (devtools/xlasan.py, enabled with
# RAY_TPU_XLASAN=1).  recompiles_total counts cache-growth events
# BEYOND a site's first compile (the first trace is the price of
# admission; every one after it is a storm candidate), tagged by the
# jit construction site (file:line).  compile_seconds observes every
# compile's wall time — untagged, one distribution per process;
# per-site cumulative seconds live in the xlasan ledger.
XLA_RECOMPILES_METRIC = "ray_tpu_xla_recompiles_total"
XLA_COMPILE_SECONDS_METRIC = "ray_tpu_xla_compile_seconds"
XLA_COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0,
                       600.0)

# Inter-node object-transfer plane, auto-recorded node-side.
# bytes_total tags: direction = in | out.  seconds tags: path =
# stream (windowed binary plane) | multi (range-split, several
# holders) | rpc (stop-and-wait control-plane fallback).
OBJECT_TRANSFER_BYTES_METRIC = "ray_tpu_object_transfer_bytes_total"
OBJECT_TRANSFER_SECONDS_METRIC = "ray_tpu_object_transfer_seconds"
OBJECT_TRANSFER_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                           5.0, 30.0)

# Control-plane RPC server telemetry, recorded by the node service's
# dispatch wrapper (and the GCS server's, surfaced through the
# gcs_status poll).  server_seconds tags: method = the rpc type
# (node handlers as-is, GCS handlers prefixed "gcs.", transfer-plane
# chunk serving as "transfer_chunk", stream delivery as
# "chan_stream").  inflight gauges handlers currently executing per
# method; queue_depth gauges the control-plane relay backlogs per
# plane = gcs_proxy (per-conn GCS relay queues) | forward (per-peer
# task-forward queues) | chan_fwd (compiled-DAG channel forwarders).
# slow_rpcs counts handlers the slow-RPC sentinel flagged (each also
# gets ONE `slow_rpc` timeline event per method per capture window,
# carrying the handler thread's stack + args summary).
# Bucket floor is 50 µs: most control RPCs are sub-millisecond;
# the tail (spill fanouts, WAL compaction holds) is what matters.
RPC_SERVER_SECONDS_METRIC = "ray_tpu_rpc_server_seconds"
RPC_SERVER_BUCKETS = (0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1, 0.5,
                      2.0, 10.0)
RPC_INFLIGHT_METRIC = "ray_tpu_rpc_inflight"
RPC_QUEUE_DEPTH_METRIC = "ray_tpu_rpc_queue_depth"
SLOW_RPC_METRIC = "ray_tpu_slow_rpcs_total"

# Scheduler decision tracing, recorded inside NodeService._schedule
# (lock already held — counters go straight into the node aggregate).
# decisions tags: outcome = local (dispatched to a local worker) |
# forward (affinity/PG-forwarded to a peer) | spill (spilled to the
# best-scored peer) | queue (stayed queued: no feasible slot yet) |
# drain_handback (re-queued by a draining node) | infeasible (failed:
# no node can ever satisfy it).  placement_seconds observes
# submit->dispatch latency per placed task (outcome tag: local |
# forward | spill).  The per-decision candidate/score detail rides in
# sampled `sched.decide` timeline spans + state.summarize_scheduling().
SCHED_DECISIONS_METRIC = "ray_tpu_sched_decisions_total"
SCHED_PLACEMENT_SECONDS_METRIC = "ray_tpu_sched_placement_seconds"
SCHED_PLACEMENT_BUCKETS = (0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0,
                           10.0, 60.0)

# THE registry lock: guards the metric registry, every metric's cell
# map, cell values, and the retry queue.  One lock (instead of the
# old per-metric locks) means cell creation, drain, and the pending
# queue can never interleave inconsistently across threads — worker,
# node, and scrape threads all mutate these maps (concurrency-
# sanitizer self-application).  Cells are created exactly ONCE per
# tagset and never replaced afterwards (drain resets them in place),
# which is what makes the pre-resolved observer() fast path's
# lock-free cell lookup sound.
_lock = threading.RLock()
_registry: List["_Metric"] = []
_flusher_started = False
# Drained-but-unpushed series retried on the next flush: a transient
# push failure must not lose counter increments.  Bounded so a dead
# node service doesn't grow memory forever.
_pending: List[dict] = []
_PENDING_MAX = 10_000

# Default histogram bucket upper bounds (seconds-ish scale).
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0)


class _Metric:
    kind = "none"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None) -> None:
        if not name:
            raise ValueError("metric name must be non-empty")
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} is not a valid Prometheus name "
                f"([a-zA-Z_:][a-zA-Z0-9_:]*)")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        # per-tagset state; subclasses define the value layout.
        # Guarded by the module registry lock `_lock`; entries are
        # create-once and reset in place at drain, never replaced.
        self._cells: Dict[Tuple[Tuple[str, str], ...], dict] = {}
        with _lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]) -> "_Metric":
        # Rebind, don't mutate: _tagset readers see either the old or
        # the new dict, never a half-updated one.
        self._default_tags = dict(tags)
        return self

    def _tagset(self, tags: Optional[Dict[str, str]]
                ) -> Tuple[Tuple[str, str], ...]:
        merged = dict(self._default_tags)
        if tags:
            unknown = set(tags) - set(self.tag_keys)
            if unknown:
                raise ValueError(
                    f"tags {sorted(unknown)} not declared in tag_keys "
                    f"{self.tag_keys} of metric {self.name!r}")
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _cell(self, tags) -> dict:
        """Resolve (create-once) the cell for a tagset.  Caller holds
        the registry lock `_lock`."""
        ts = self._tagset(tags)
        cell = self._cells.get(ts)
        if cell is None:
            cell = self._new_cell()
            self._cells[ts] = cell
        return cell

    def _new_cell(self) -> dict:
        raise NotImplementedError

    def _drain_locked(self) -> List[dict]:
        """Caller holds the registry lock `_lock`."""
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter (reference: util/metrics.py:115)."""

    kind = "counter"

    def _new_cell(self) -> dict:
        return {"delta": 0.0}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        with _lock:
            self._cell(tags)["delta"] += value

    def _drain_locked(self) -> List[dict]:
        out = []
        for ts, cell in self._cells.items():
            if cell["delta"]:
                out.append({"name": self.name, "kind": "counter",
                            "tags": dict(ts),
                            "value": cell["delta"],
                            "description": self.description})
                cell["delta"] = 0.0
        return out


# Tag keys whose presence marks a gauge series as PER-INSTANCE (one
# series per engine/replica/train-run instance, minted at runtime):
# the leak ledger tracks their cells from first set() to remove() —
# the RT015 class, observed live.  Statically-tagged series
# (object_store_bytes {kind}) live for the process by design and are
# not tracked.
_INSTANCE_SERIES_TAGS = ("engine", "run")


class Gauge(_Metric):
    """Last-write-wins value (reference: util/metrics.py:188)."""

    kind = "gauge"

    def _new_cell(self) -> dict:
        return {"value": 0.0, "dirty": False}

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        ts = self._tagset(tags)
        with _lock:
            cell = self._cells.get(ts)
            fresh = cell is None
            if fresh:
                cell = self._new_cell()
                self._cells[ts] = cell
            cell["value"] = float(value)
            cell["dirty"] = True
        if fresh and leaksan._ENABLED and any(
                k in _INSTANCE_SERIES_TAGS for k, _ in ts):
            # Outside the registry lock: the ledger's metric sinks may
            # construct metrics of their own.
            leaksan.register("metric_series", (self.name, ts))

    def _drain_locked(self) -> List[dict]:
        out = []
        for ts, cell in self._cells.items():
            if cell["dirty"]:
                out.append({"name": self.name, "kind": "gauge",
                            "tags": dict(ts),
                            "value": cell["value"],
                            "description": self.description})
                cell["dirty"] = False
        return out

    def remove(self, tags: Optional[Dict[str, str]] = None,
               force: bool = False) -> None:
        """Drop one series' cell from this process's registry,
        queueing a final zero sample so the node-side aggregate
        (push-model: series are never deleted there) reads 0 rather
        than the last live value.  For per-instance-tagged gauges
        (e.g. the paged-KV engine series) this keeps repeated
        construct/stop cycles from accumulating dead cells forever.

        ``force=True`` queues the zero sample even when THIS process
        never wrote the series — cross-process cleanup of a dead
        writer's samples (the Serve controller zeroing an uncleanly
        killed replica's per-engine gauges, whose own registry died
        with it)."""
        ts = self._tagset(tags)
        with _lock:
            # One lock for pop + pending enqueue: the old split
            # (per-metric lock, then registry lock) let a flush slip
            # between them and push the zero before a straggler set().
            popped = self._cells.pop(ts, None) is not None
            if popped or force:
                _pending.append({"name": self.name, "kind": "gauge",
                                 "tags": dict(ts), "value": 0.0,
                                 "description": self.description})
        if popped and leaksan._ENABLED:
            leaksan.discharge("metric_series", (self.name, ts),
                              expect=False)


class Histogram(_Metric):
    """Bucketed distribution (reference: util/metrics.py:263)."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None) -> None:
        self.boundaries = tuple(sorted(boundaries or DEFAULT_BUCKETS))
        if not self.boundaries:
            raise ValueError("histogram needs at least one boundary")
        for lo, hi in zip(self.boundaries, self.boundaries[1:]):
            if not lo < hi:
                raise ValueError(
                    f"histogram boundaries must be strictly increasing "
                    f"(got duplicate {lo})")
        if any(not math.isfinite(b) for b in self.boundaries):
            raise ValueError("histogram boundaries must be finite "
                             "(+Inf is implicit)")
        super().__init__(name, description, tag_keys)

    def _new_cell(self) -> dict:
        return {"buckets": {str(b): 0 for b in self.boundaries},
                "sum": 0.0, "count": 0}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        with _lock:
            cell = self._cell(tags)
            for b in self.boundaries:
                if value <= b:
                    cell["buckets"][str(b)] += 1
                    break
            cell["sum"] += value
            cell["count"] += 1

    def observer(self, tags: Optional[Dict[str, str]] = None):
        """Pre-resolved observe callable for one tag set — hot paths
        (compiled-DAG hops at µs rates) skip the per-call tag
        merge/sort AND the cell-map lookup: the cell object is
        resolved once here (create-once under the registry lock) and
        pinned in the closure.  Sound because histogram cells are
        never replaced — _drain_locked resets them in place — so the
        pinned reference can't go stale (the old check-then-act
        re-resolution re-created cells racing the drain)."""
        boundaries = self.boundaries
        with _lock:
            cell = self._cell(tags)

        def obs(value: float) -> None:
            with _lock:
                for b in boundaries:
                    if value <= b:
                        cell["buckets"][str(b)] += 1
                        break
                cell["sum"] += value
                cell["count"] += 1

        return obs

    def _drain_locked(self) -> List[dict]:
        out = []
        for ts, cell in self._cells.items():
            if cell["count"]:
                out.append({"name": self.name, "kind": "histogram",
                            "tags": dict(ts),
                            "value": 0.0,
                            "buckets": dict(cell["buckets"]),
                            "sum": cell["sum"],
                            "count": cell["count"],
                            "description": self.description})
                # Reset IN PLACE: observer() closures pin this dict.
                for k in cell["buckets"]:
                    cell["buckets"][k] = 0
                cell["sum"] = 0.0
                cell["count"] = 0
        return out


_shared_counters: Dict[Tuple[str, Tuple[str, ...]], "Counter"] = {}
_shared_histograms: Dict[Tuple[str, Tuple[str, ...]], "Histogram"] = {}
_shared_gauges: Dict[Tuple[str, Tuple[str, ...]], "Gauge"] = {}


def shared_counter(name: str, description: str = "",
                   tag_keys: Sequence[str] = ()) -> "Counter":
    """Process-wide singleton Counter by (name, tag_keys) — for runtime
    subsystems (chaos injector, Serve router) that bump a counter from
    arbitrary call sites without each reinventing a lazy global."""
    key = (name, tuple(tag_keys))
    with _lock:
        c = _shared_counters.get(key)
        if c is None:
            c = Counter(name, description=description,
                        tag_keys=tag_keys)
            _shared_counters[key] = c
        return c


def shared_gauge(name: str, description: str = "",
                 tag_keys: Sequence[str] = ()) -> "Gauge":
    """shared_counter's Gauge sibling (the Serve controller sets
    replica/queue-depth gauges from several loops without each
    reinventing a lazy global)."""
    key = (name, tuple(tag_keys))
    with _lock:
        g = _shared_gauges.get(key)
        if g is None:
            g = Gauge(name, description=description, tag_keys=tag_keys)
            _shared_gauges[key] = g
        return g


def shared_histogram(name: str, description: str = "",
                     boundaries: Sequence[float] = (),
                     tag_keys: Sequence[str] = ()) -> "Histogram":
    """shared_counter's Histogram sibling (compiled-DAG executors
    observe per-hop latencies from worker processes)."""
    key = (name, tuple(tag_keys))
    with _lock:
        h = _shared_histograms.get(key)
        if h is None:
            h = Histogram(name, description=description,
                          boundaries=list(boundaries) or None,
                          tag_keys=tag_keys)
            _shared_histograms[key] = h
        return h


# ---------------------------------------------------------------------------
# flush + scrape
# ---------------------------------------------------------------------------
def flush() -> None:
    """Push pending deltas to the node service now (also called by the
    daemon flusher).  Failed pushes requeue the drained batch.

    Drain runs under the registry lock (consistent snapshot across
    every metric); the network push runs OUTSIDE it — blocking on the
    node service while holding the lock would convoy every writer
    (the RT011 class)."""
    global _pending
    client = get_global_client()
    if client is None:
        return
    with _lock:
        batch, _pending = list(_pending), []
        for m in _registry:
            batch.extend(m._drain_locked())
    if not batch:
        return
    try:
        client.metrics_push(batch)
    except Exception:
        with _lock:
            _pending = (batch + _pending)[:_PENDING_MAX]


def _ensure_flusher() -> None:
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        # Process-lifetime singleton BY DESIGN: every process that
        # touches a metric needs exactly one flusher until exit, and
        # a stop knob would add a shutdown ordering hazard for zero
        # benefit (the daemon dies with the process; pending deltas
        # are pushed by the final flush() in scrape paths).
        while True:      # ray-tpu: noqa[RT014]
            time.sleep(FLUSH_INTERVAL_S)
            flush()

    threading.Thread(target=loop, daemon=True,
                     name="rtpu-metrics-flusher").start()


def scrape() -> List[dict]:
    """Merged series from the node service (includes runtime built-ins
    like ray_tpu_tasks_pending and object-store usage)."""
    flush()
    client = get_global_client()
    if client is None:
        raise RuntimeError("ray_tpu is not initialized")
    return client.metrics_scrape()


def _escape_label_value(v: str) -> str:
    """Label-value escaping per the exposition format spec: backslash,
    double-quote, and line-feed must be escaped."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    """HELP-text escaping per the spec: backslash and line-feed."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _labels(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                           for k, v in sorted(tags.items())) + "}")


def prometheus_text() -> str:
    """Render `scrape()` in the Prometheus exposition format the
    reference's metrics agent serves.  Histograms emit cumulative
    buckets ending in the mandatory `+Inf` bucket, which always equals
    `_count` (spec: the +Inf bucket counts all observations, including
    those above the largest declared boundary)."""
    lines: List[str] = []
    seen_help = set()
    for s in sorted(scrape(), key=lambda s: s["name"]):
        name = s["name"]
        if name not in seen_help:
            seen_help.add(name)
            if s.get("description"):
                lines.append(
                    f"# HELP {name} {_escape_help(s['description'])}")
            lines.append(f"# TYPE {name} {s['kind']}")
        tags = s.get("tags") or {}
        label = _labels(tags)
        if s["kind"] == "histogram":
            count = int(s["count"])
            acc = 0
            for b in sorted(s["buckets"], key=float):
                acc += s["buckets"][b]
                lines.append(
                    f"{name}_bucket{_labels(dict(tags, le=b))} {acc}")
            # +Inf is cumulative over ALL observations; guard against a
            # malformed merge where bucket sums exceed the count so the
            # series stays monotone.
            inf = max(count, acc)
            lines.append(
                f"{name}_bucket{_labels(dict(tags, le='+Inf'))} {inf}")
            lines.append(f"{name}_sum{label} {s['sum']}")
            lines.append(f"{name}_count{label} {inf}")
        else:
            lines.append(f"{name}{label} {s['value']}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# shared percentile math
# ---------------------------------------------------------------------------
# THE percentile implementations: the stall sentinel's histogram-cell
# quantile (node_service), the state-API sample percentile, the serve
# replica/engine p95 helpers, and the slow-RPC threshold all call
# these two — one definition of "p95" across the runtime instead of
# three drifting copies.

def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an ASCENDING-sorted sequence
    (0 <= q <= 1).  Returns 0.0 on empty input."""
    if not sorted_vals:
        return 0.0
    idx = min(int(len(sorted_vals) * q), len(sorted_vals) - 1)
    return sorted_vals[idx]


def hist_quantile(cell: dict, q: float) -> float:
    """Quantile estimate from an aggregated histogram cell
    ``{"buckets": {str(bound): n}, "count": N}`` (the node-side merge
    layout): the upper bound of the bucket where the cumulative count
    crosses ``q * count``.  Observations above the largest declared
    boundary land in the implicit +Inf bucket; for those the largest
    finite boundary is returned (a conservative underestimate).
    Returns 0.0 when the cell is empty."""
    count = int(cell.get("count") or 0)
    if count <= 0:
        return 0.0
    target = q * count
    acc = 0
    bounds = sorted(cell.get("buckets") or {}, key=float)
    for b in bounds:
        acc += cell["buckets"][b]
        if acc >= target:
            return float(b)
    return float(bounds[-1]) if bounds else 0.0
