"""Scheduling strategies (reference: util/scheduling_strategies.py).

* PlacementGroupSchedulingStrategy (:15) — run in a PG bundle.
* NodeAffinitySchedulingStrategy (:41) — pin to a node id; `soft=True`
  falls back to normal scheduling if the node is gone, hard affinity
  fails the task/actor with NodeAffinityError.
* "SPREAD" / "DEFAULT" string strategies — accepted for parity
  ("SPREAD" is best-effort here: the hybrid scheduler's spill logic
  already distributes load).

Pass via options:  f.options(scheduling_strategy=...).remote()
"""

from __future__ import annotations

from typing import Optional, Union


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1) -> None:
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: Union[str, bytes],
                 soft: bool = False) -> None:
        self.node_id = (bytes.fromhex(node_id)
                        if isinstance(node_id, str) else node_id)
        self.soft = soft


SchedulingStrategyT = Union[None, str, PlacementGroupSchedulingStrategy,
                            NodeAffinitySchedulingStrategy]


def apply_to_options(options: dict) -> dict:
    """Fold a `scheduling_strategy` option into the primitive option
    keys the submission path understands.  Returns the same dict."""
    strat = options.pop("scheduling_strategy", None)
    if strat is None or strat in ("DEFAULT", "SPREAD"):
        return options
    if isinstance(strat, PlacementGroupSchedulingStrategy):
        options.setdefault("placement_group", strat.placement_group)
        if strat.placement_group_bundle_index >= 0:
            options.setdefault("placement_group_bundle_index",
                               strat.placement_group_bundle_index)
        return options
    if isinstance(strat, NodeAffinitySchedulingStrategy):
        options["_affinity"] = {"node_id": strat.node_id,
                                "soft": strat.soft}
        return options
    raise TypeError(f"unsupported scheduling_strategy: {strat!r}")
