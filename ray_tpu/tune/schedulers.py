"""Trial schedulers: FIFO and ASHA early stopping.

Analog of the reference's tune/schedulers/async_hyperband.py
(ASHAScheduler / AsyncHyperBandScheduler): rungs at
grace_period * reduction_factor^k; when a trial reports at (or past) a
rung it joins that rung's score record and is stopped unless it sits in
the top 1/reduction_factor of everything recorded there — the
asynchronous successive-halving rule (no waiting for full brackets).
"""

from __future__ import annotations

from typing import Any, Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping: every trial runs to completion."""

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3) -> None:
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.rf = reduction_factor
        # Rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # milestone -> list of recorded scores (sign-normalized: higher
        # is always better internally)
        self._rungs: Dict[int, List[float]] = {m: []
                                               for m in self.milestones}
        # trial_id -> highest milestone already recorded
        self._reached: Dict[str, int] = {}

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE   # e.g. a final summary report — tolerate
        t = int(result.get(self.time_attr, 0))
        score = self._score(result)
        decision = CONTINUE
        for m in self.milestones:
            if t < m or self._reached.get(trial_id, 0) >= m:
                continue
            self._reached[trial_id] = m
            rung = self._rungs[m]
            rung.append(score)
            # Top 1/rf cutoff over everything recorded at this rung.
            k = max(len(rung) // self.rf, 1)
            cutoff = sorted(rung, reverse=True)[k - 1]
            if score < cutoff:
                decision = STOP
        return decision
