"""Trial schedulers: FIFO and ASHA early stopping.

Analog of the reference's tune/schedulers/async_hyperband.py
(ASHAScheduler / AsyncHyperBandScheduler): rungs at
grace_period * reduction_factor^k; when a trial reports at (or past) a
rung it joins that rung's score record and is stopped unless it sits in
the top 1/reduction_factor of everything recorded there — the
asynchronous successive-halving rule (no waiting for full brackets).
"""

from __future__ import annotations

from typing import Any, Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping: every trial runs to completion."""

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3) -> None:
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.rf = reduction_factor
        # Rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # milestone -> list of recorded scores (sign-normalized: higher
        # is always better internally)
        self._rungs: Dict[int, List[float]] = {m: []
                                               for m in self.milestones}
        # trial_id -> highest milestone already recorded
        self._reached: Dict[str, int] = {}

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE   # e.g. a final summary report — tolerate
        t = int(result.get(self.time_attr, 0))
        score = self._score(result)
        decision = CONTINUE
        for m in self.milestones:
            if t < m or self._reached.get(trial_id, 0) >= m:
                continue
            self._reached[trial_id] = m
            rung = self._rungs[m]
            rung.append(score)
            # Top 1/rf cutoff over everything recorded at this rung.
            k = max(len(rung) // self.rf, 1)
            cutoff = sorted(rung, reverse=True)[k - 1]
            if score < cutoff:
                decision = STOP
        return decision


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (reference: tune/schedulers/pbt.py): at every
    `perturbation_interval` iterations, trials in the bottom quantile
    EXPLOIT a top-quantile peer — clone its checkpoint and config —
    then EXPLORE by mutating hyperparameters (resample with
    `resample_probability`, else perturb x1.2 / x0.8, or step through
    explicit choice lists).

    Decisions are either CONTINUE/STOP strings or an exploit dict
    {"decision": "EXPLOIT", "source": trial_id, "config": new_config}
    the controller acts on (restart from source's checkpoint)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int = 0) -> None:
        import random
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations must be non-empty")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations)
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    # controller hook: called at trial start and after exploit restarts
    def register_trial(self, trial_id: str,
                       config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for key, domain in self.mutations.items():
            if isinstance(domain, list):
                if self._rng.random() < self.resample_p \
                        or key not in out:
                    out[key] = self._rng.choice(domain)
                else:  # step to a neighboring choice
                    try:
                        i = domain.index(out[key])
                    except ValueError:
                        i = 0
                    i = max(0, min(len(domain) - 1,
                                   i + self._rng.choice((-1, 1))))
                    out[key] = domain[i]
            elif callable(domain):
                if self._rng.random() < self.resample_p \
                        or key not in out:
                    out[key] = domain()
                else:
                    out[key] = out[key] * self._rng.choice((0.8, 1.2))
            else:
                raise TypeError(
                    f"mutation for {key!r} must be a list of choices "
                    f"or a zero-arg sampler")
        return out

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        if self.metric not in result:
            return CONTINUE
        v = float(result[self.metric])
        self._scores[trial_id] = v if self.mode == "max" else -v
        t = int(result.get(self.time_attr, 0))
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        if len(self._scores) < 2:
            return CONTINUE
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(int(len(ranked) * self.quantile), 1)
        bottom, top = ranked[:k], ranked[-k:]
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        source = self._rng.choice(top)
        src_cfg = self._configs.get(source)
        if src_cfg is None:
            return CONTINUE
        return {"decision": EXPLOIT, "source": source,
                "config": self._mutate(src_cfg)}
