"""Trial schedulers: FIFO and ASHA early stopping.

Analog of the reference's tune/schedulers/async_hyperband.py
(ASHAScheduler / AsyncHyperBandScheduler): rungs at
grace_period * reduction_factor^k; when a trial reports at (or past) a
rung it joins that rung's score record and is stopped unless it sits in
the top 1/reduction_factor of everything recorded there — the
asynchronous successive-halving rule (no waiting for full brackets).
"""

from __future__ import annotations

from typing import Any, Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping: every trial runs to completion."""

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 3) -> None:
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.rf = reduction_factor
        # Rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # milestone -> list of recorded scores (sign-normalized: higher
        # is always better internally)
        self._rungs: Dict[int, List[float]] = {m: []
                                               for m in self.milestones}
        # trial_id -> highest milestone already recorded
        self._reached: Dict[str, int] = {}

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE   # e.g. a final summary report — tolerate
        t = int(result.get(self.time_attr, 0))
        score = self._score(result)
        decision = CONTINUE
        for m in self.milestones:
            if t < m or self._reached.get(trial_id, 0) >= m:
                continue
            self._reached[trial_id] = m
            rung = self._rungs[m]
            rung.append(score)
            # Top 1/rf cutoff over everything recorded at this rung.
            k = max(len(rung) // self.rf, 1)
            cutoff = sorted(rung, reverse=True)[k - 1]
            if score < cutoff:
                decision = STOP
        return decision


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (reference: tune/schedulers/pbt.py): at every
    `perturbation_interval` iterations, trials in the bottom quantile
    EXPLOIT a top-quantile peer — clone its checkpoint and config —
    then EXPLORE by mutating hyperparameters (resample with
    `resample_probability`, else perturb x1.2 / x0.8, or step through
    explicit choice lists).

    Decisions are either CONTINUE/STOP strings or an exploit dict
    {"decision": "EXPLOIT", "source": trial_id, "config": new_config}
    the controller acts on (restart from source's checkpoint)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int = 0) -> None:
        import random
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations must be non-empty")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations)
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    # controller hook: called at trial start and after exploit restarts
    def register_trial(self, trial_id: str,
                       config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for key, domain in self.mutations.items():
            if isinstance(domain, list):
                if self._rng.random() < self.resample_p \
                        or key not in out:
                    out[key] = self._rng.choice(domain)
                else:  # step to a neighboring choice
                    try:
                        i = domain.index(out[key])
                    except ValueError:
                        i = 0
                    i = max(0, min(len(domain) - 1,
                                   i + self._rng.choice((-1, 1))))
                    out[key] = domain[i]
            elif callable(domain):
                if self._rng.random() < self.resample_p \
                        or key not in out:
                    out[key] = domain()
                else:
                    out[key] = out[key] * self._rng.choice((0.8, 1.2))
            else:
                raise TypeError(
                    f"mutation for {key!r} must be a list of choices "
                    f"or a zero-arg sampler")
        return out

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        if self.metric not in result:
            return CONTINUE
        v = float(result[self.metric])
        self._scores[trial_id] = v if self.mode == "max" else -v
        t = int(result.get(self.time_attr, 0))
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        if len(self._scores) < 2:
            return CONTINUE
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(int(len(ranked) * self.quantile), 1)
        bottom, top = ranked[:k], ranked[-k:]
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        source = self._rng.choice(top)
        src_cfg = self._configs.get(source)
        if src_cfg is None:
            return CONTINUE
        return {"decision": EXPLOIT, "source": source,
                "config": self._mutate(src_cfg)}


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: tune/schedulers/pb2.py:256
    PB2 — Parker-Holder et al., NeurIPS'20): the PBT scaffold
    (quantiles, checkpoint exploit) is unchanged, but the EXPLORE step
    replaces random x0.8/x1.2 perturbation with a time-varying GP
    bandit: every `perturbation_interval` the scheduler records
    (hyperparams, t) -> reward-improvement datapoints from all trials,
    fits a GP with an RBF kernel over normalized (config, time), and
    picks the exploiting trial's new config by maximizing the UCB
    acquisition mu + kappa*sigma within `hyperparam_bounds`.

    Continuous bounds only, matching the reference
    (pb2.py:339 hyperparam_bounds: {key: [min, max]}).
    """

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 2,
                 hyperparam_bounds: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25,
                 kappa: float = 2.0, seed: int = 0) -> None:
        if not hyperparam_bounds:
            raise ValueError("hyperparam_bounds must be non-empty")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        for k, (lo, hi) in self.bounds.items():
            if not hi > lo:
                raise ValueError(f"bounds for {k!r} must have hi > lo")
        self.kappa = kappa
        # Parent needs non-empty mutations for its invariants; PB2
        # overrides _mutate, so give it in-bounds uniform samplers as
        # the (never-reached) fallback shape.
        mutations = {k: (lambda lo=lo, hi=hi:
                         __import__("random").uniform(lo, hi))
                     for k, (lo, hi) in self.bounds.items()}
        super().__init__(metric, mode=mode, time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations=mutations,
                         quantile_fraction=quantile_fraction,
                         resample_probability=0.0, seed=seed)
        self._keys = sorted(self.bounds)
        self._X: List[List[float]] = []    # normalized config + raw t
        self._y: List[float] = []          # reward delta over interval
        self._prev: Dict[str, tuple] = {}  # trial -> (t, score)
        self._max_points = 512             # GP refit is O(n^3); window

    def register_trial(self, trial_id: str,
                       config: Dict[str, Any]) -> None:
        """Called at trial start AND after exploit restarts: the trial
        resumes from a DIFFERENT checkpoint, so the previous score is
        not a valid delta baseline — drop it or the checkpoint jump
        would be credited to the new config as reward improvement."""
        super().register_trial(trial_id, config)
        self._prev.pop(trial_id, None)

    def _norm(self, key: str, value: float) -> float:
        lo, hi = self.bounds[key]
        return min(1.0, max(0.0, (float(value) - lo) / (hi - lo)))

    def on_result(self, trial_id: str, result: Dict[str, Any]):
        # Record (config, t) -> score-delta datapoints BEFORE the PBT
        # quantile logic runs (which may replace this trial's config).
        if self.metric in result:
            v = float(result[self.metric])
            s = v if self.mode == "max" else -v
            t = int(result.get(self.time_attr, 0))
            prev = self._prev.get(trial_id)
            if prev is None:
                self._prev[trial_id] = (t, s)
            elif t - prev[0] >= self.interval:
                cfg = self._configs.get(trial_id)
                if cfg is not None and all(k in cfg
                                           for k in self._keys):
                    x = [self._norm(k, cfg[k]) for k in self._keys]
                    self._X.append(x + [float(t)])
                    self._y.append(s - prev[1])
                    if len(self._y) > self._max_points:
                        self._X = self._X[-self._max_points:]
                        self._y = self._y[-self._max_points:]
                self._prev[trial_id] = (t, s)
        return super().on_result(trial_id, result)

    @staticmethod
    def _rbf(A, B, ell: float):
        import numpy as np
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (ell * ell))

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """GP-UCB explore step (replaces PBT's random perturbation)."""
        import numpy as np
        out = dict(config)
        if len(self._y) < 4:
            for k in self._keys:              # cold start: random
                lo, hi = self.bounds[k]
                out[k] = self._rng.uniform(lo, hi)
            return out
        X = np.asarray(self._X, dtype=np.float64)
        tmax = max(float(X[:, -1].max()), 1.0)
        Xn = X.copy()
        Xn[:, -1] /= tmax                     # config dims already 0-1
        y = np.asarray(self._y, dtype=np.float64)
        y_std = float(y.std()) or 1.0
        yn = (y - y.mean()) / y_std
        ell, noise = 0.25, 1e-2
        K = self._rbf(Xn, Xn, ell) + noise * np.eye(len(Xn))
        alpha = np.linalg.solve(K, yn)
        # Candidates: uniform in bounds + jitter around the rows with
        # the best observed improvement (exploit the GP's evidence).
        cands = [[self._rng.random() for _ in self._keys]
                 for _ in range(64)]
        for row in Xn[np.argsort(yn)[-8:], :-1]:
            cands.append([min(1.0, max(0.0,
                                       float(v) + self._rng.gauss(0, 0.1)))
                          for v in row])
        C = np.asarray(cands, dtype=np.float64)
        t_now = float(X[:, -1].max()) / tmax
        Cfull = np.concatenate(
            [C, np.full((len(C), 1), t_now)], axis=1)
        Kc = self._rbf(Cfull, Xn, ell)
        mu = Kc @ alpha
        var = 1.0 + noise - np.einsum(
            "ij,ji->i", Kc, np.linalg.solve(K, Kc.T))
        ucb = mu + self.kappa * np.sqrt(np.maximum(var, 1e-9))
        best = C[int(np.argmax(ucb))]
        for k, v in zip(self._keys, best):
            lo, hi = self.bounds[k]
            out[k] = lo + float(v) * (hi - lo)
        return out


class MedianStoppingRule:
    """Median stopping (reference: tune/schedulers/
    median_stopping_rule.py MedianStoppingRule — the Vizier rule): a
    trial is stopped at step t when its best result so far is worse
    than the median of the OTHER trials' running means up to t, after
    `grace_period` steps and once `min_samples_required` trials have
    reported."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1,
                 min_samples_required: int = 3) -> None:
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[tuple]] = {}   # trial -> (t, score)

    def _running_mean(self, trial_id: str, t: int) -> float:
        pts = [s for tt, s in self._history.get(trial_id, ()) if tt <= t]
        return sum(pts) / len(pts) if pts else float("-inf")

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        t = int(result.get(self.time_attr, 0))
        self._history.setdefault(trial_id, []).append((t, score))
        if t < self.grace_period:
            return CONTINUE
        others = [self._running_mean(tid, t) for tid in self._history
                  if tid != trial_id and self._history[tid]]
        others = [m for m in others if m != float("-inf")]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(s for _, s in self._history[trial_id])
        return STOP if best < median else CONTINUE


PAUSE = "PAUSE"


class HyperBandScheduler:
    """Synchronous HyperBand / successive halving (reference:
    tune/schedulers/hyperband.py HyperBandScheduler).

    Where ASHA decides from whatever is recorded at a rung so far
    (asynchronous, never waits), HyperBand SYNCHRONIZES each rung:
    every member of a bracket pauses at the milestone, and only when
    the whole bracket has arrived does the top 1/reduction_factor
    resume — the rest stop.  That needs runner support for pausing
    (checkpoint, release the slot, resume later), which the Tuner
    provides via the PAUSE decision + `pop_runnable()` poll.

    Brackets have FIXED capacity rf^depth and fill in registration
    order; a new bracket opens when the current one is full (the
    reference's incremental bracket construction).  With
    `num_brackets > 1` consecutive brackets drop their first rungs,
    trading early-stopping aggressiveness for protection of slow
    starters — the HyperBand paper's s-sweep.  `seal()` (called by the
    runner when no further trials will ever register) closes the last
    under-full bracket so its rungs release on whoever arrived.
    """

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, grace_period: int = 1,
                 reduction_factor: int = 3,
                 num_brackets: int = 1) -> None:
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.rf = reduction_factor
        ladder = []
        t = grace_period
        while t < max_t:
            ladder.append(t)
            t *= reduction_factor
        self._ladders = [ladder[b:] or [max_t]
                         for b in range(max(num_brackets, 1))]
        # Bracket instances: {"ladder", "cap", "members", "sealed"}
        self._brackets: List[dict] = []
        self._bracket_of: Dict[str, int] = {}      # trial -> index
        self._rung: Dict[tuple, Dict[str, float]] = {}
        self._released: set = set()                # (bracket_ix, m)
        self._dead: set = set()
        self._release: Dict[str, str] = {}         # tid -> verdict
        self._sealed_all = False

    def _new_bracket(self) -> dict:
        ladder = self._ladders[len(self._brackets) % len(self._ladders)]
        br = {"ladder": ladder, "cap": self.rf ** len(ladder),
              "members": [], "sealed": False}
        self._brackets.append(br)
        return br

    def register_trial(self, trial_id: str,
                       config: Dict[str, Any]) -> None:
        if trial_id in self._bracket_of:
            return          # rung resume re-launch, not a new trial
        br = self._brackets[-1] if self._brackets else None
        if br is None or len(br["members"]) >= br["cap"]:
            br = self._new_bracket()
        br["members"].append(trial_id)
        self._bracket_of[trial_id] = len(self._brackets) - 1

    def seal(self) -> None:
        """No further registrations will come: under-full brackets
        release on whoever arrived."""
        if self._sealed_all:
            return
        self._sealed_all = True
        for ix, br in enumerate(self._brackets):
            br["sealed"] = True
            for m in br["ladder"]:
                self._maybe_release(ix, m)

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        if self.metric not in result:
            return CONTINUE
        ix = self._bracket_of.get(trial_id)
        if ix is None:
            return CONTINUE
        t = int(result.get(self.time_attr, 0))
        for m in self._brackets[ix]["ladder"]:
            rung = self._rung.setdefault((ix, m), {})
            if t >= m and trial_id not in rung:
                rung[trial_id] = self._score(result)
                self._maybe_release(ix, m)
                # Pause at the first newly-reached rung; if this was
                # the last arriver the verdicts are already queued in
                # _release and the runner applies them post-pause.
                return PAUSE
        return CONTINUE

    def on_trial_remove(self, trial_id: str) -> None:
        """Trial finished/errored outside scheduler control: bracket
        peers must not wait for it."""
        self._dead.add(trial_id)
        ix = self._bracket_of.get(trial_id)
        if ix is None:
            return
        for m in self._brackets[ix]["ladder"]:
            self._maybe_release(ix, m)

    def _maybe_release(self, ix: int, m: int) -> None:
        if (ix, m) in self._released:
            return
        br = self._brackets[ix]
        full = br["sealed"] or len(br["members"]) >= br["cap"]
        rung = self._rung.get((ix, m), {})
        live = [tid for tid in br["members"] if tid not in self._dead]
        if not full or not rung \
                or any(tid not in rung for tid in live):
            return
        self._released.add((ix, m))
        arrived = [tid for tid in rung if tid not in self._dead]
        if not arrived:
            return
        k = max(len(arrived) // self.rf, 1)
        ranked = sorted(arrived, key=lambda tid: rung[tid],
                        reverse=True)
        for i, tid in enumerate(ranked):
            keep = i < k
            self._release[tid] = "RESUME" if keep else "STOP"
            if not keep:
                # Stopped members must not hold up higher rungs.
                self._dead.add(tid)

    def pop_runnable(self) -> Dict[str, str]:
        """Runner poll: {trial_id: RESUME|STOP} decided since the last
        call."""
        out, self._release = self._release, {}
        return out
