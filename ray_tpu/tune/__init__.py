"""ray_tpu.tune: hyperparameter search over the actor runtime.

Analog of the reference's Ray Tune (python/ray/tune): Tuner
(tune/tuner.py:44) + trial controller (execution/tune_controller.py:68)
+ search spaces (search/sample.py) + ASHA early stopping
(schedulers/async_hyperband.py) + PBT (schedulers/pbt.py).  Trials are actors reporting through
the same crash-surviving KV channel as Train workers, and a TpuTrainer
can be passed as the trainable (Train-on-Tune,
train/base_trainer.py:693).
"""

from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     HyperBandScheduler,
                                     MedianStoppingRule, PB2,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (BOHBSearcher, ExternalSearcher,
                                 TPESearcher, choice,
                                 grid_search, loguniform, randint,
                                 uniform)
from ray_tpu.tune.tuner import (ResultGrid, TuneConfig, Tuner,
                                with_parameters)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "ASHAScheduler",
    "HyperBandScheduler", "PopulationBasedTraining", "PB2",
    "MedianStoppingRule", "FIFOScheduler", "grid_search", "uniform",
    "loguniform", "randint", "choice", "TPESearcher", "BOHBSearcher", "ExternalSearcher",
    "with_parameters",
]
