"""Tuner + trial controller.

Analog of the reference's Tuner (tune/tuner.py:44) driving the
TuneController event loop (tune/execution/tune_controller.py:68): each
trial is one actor running the trainable function with the same
session.report KV write-through the Train workers use; the controller
drains reports, feeds the scheduler, and kills trials it says to stop.

Train-on-Tune parity (train/base_trainer.py:693-724 — the reference
runs EVERY Train job as a Tune trial): pass a TpuTrainer as the
trainable and each trial calls trainer.fit() with the variant's
`train_loop_config` merged in.
"""

from __future__ import annotations

import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train import session as session_mod
from ray_tpu.tune.schedulers import (CONTINUE, PAUSE, STOP,
                                     FIFOScheduler)
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    # Whole-experiment wall-clock budget (reference: time_budget_s):
    # once exceeded, nothing new launches and running trials stop
    # with their last reported metrics.
    time_budget_s: Optional[float] = None
    scheduler: Any = None
    # Model-based searcher (e.g. tune.search.TPESearcher): suggests a
    # config per trial and observes completions (reference:
    # tune/search/optuna/optuna_search.py role).
    search_alg: Any = None
    seed: int = 0


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    status: str = "PENDING"   # RUNNING|TERMINATED|EARLY_STOPPED|ERROR
    path: str = ""


class ResultGrid:
    def __init__(self, results: List[TrialResult]) -> None:
        self._results = results

    def __len__(self) -> int:
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str, mode: str = "max"
                        ) -> TrialResult:
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]          # noqa: E731
        return (max if mode == "max" else min)(scored, key=key)

    def get_dataframe(self) -> List[Dict[str, Any]]:
        return [dict(r.metrics, trial_id=r.trial_id,
                     status=r.status) for r in self._results]


@ray_tpu.remote
class _TrialActor:
    """One trial in its own worker process (reference: a Tune trial's
    train-fn ray actor)."""

    def __init__(self, trial_id: str, trial_dir: str,
                 config: Dict[str, Any], report_ns: str,
                 restore_checkpoint: Optional[str] = None) -> None:
        ctx = session_mod.TrainContext(
            world_size=1, world_rank=0, trial_dir=trial_dir,
            restore_checkpoint=restore_checkpoint, config=config,
            report_ns=report_ns)
        session_mod.set_context(ctx)
        self._config = config

    def run(self, fn: Callable) -> Optional[str]:
        try:
            fn(self._config)
            return None
        except BaseException as e:   # noqa: BLE001
            import traceback
            return "".join(traceback.format_exception(
                type(e), e, e.__traceback__))


class Tuner:
    def __init__(self, trainable: Union[Callable, Any],
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[Any] = None) -> None:
        from ray_tpu.train.trainer import RunConfig, TpuTrainer
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._param_space = dict(param_space or {})
        self._restored_trials: Optional[List[TrialResult]] = None
        if isinstance(trainable, TpuTrainer):
            self._fn = _trainer_trainable(trainable)
        elif callable(trainable):
            self._fn = trainable
        else:
            raise TypeError("trainable must be a function or TpuTrainer")

    # -- experiment state (reference: tune/execution/experiment_state.py
    # periodic snapshots + Tuner.restore) ------------------------------
    def _save_experiment_state(self, exp_dir: str,
                               trials: List[TrialResult]) -> None:
        state = {"param_space": self._param_space,
                 "trials": [{"trial_id": t.trial_id, "config": t.config,
                             "metrics": t.metrics, "history": t.history,
                             "checkpoint": (t.checkpoint.path
                                            if t.checkpoint else None),
                             "error": t.error, "status": t.status,
                             "path": t.path} for t in trials]}
        tmp = os.path.join(exp_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))

    @classmethod
    def restore(cls, path: str, trainable: Union[Callable, Any],
                *, tune_config: Optional[TuneConfig] = None,
                run_config: Optional[Any] = None) -> "Tuner":
        """Resume an interrupted sweep from its experiment directory:
        finished trials keep their results, unfinished ones re-run
        (from their last checkpoint when present).  Reference:
        Tuner.restore over experiment-state snapshots."""
        from ray_tpu.train.trainer import RunConfig
        with open(os.path.join(path, "experiment_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        if isinstance(state, list):            # pre-param_space format
            state = {"param_space": {}, "trials": state}
        rc = run_config or RunConfig()
        rc.name = os.path.basename(path.rstrip("/"))
        rc.storage_path = os.path.dirname(path.rstrip("/"))
        tuner = cls(trainable, param_space=state["param_space"],
                    tune_config=tune_config, run_config=rc)
        trials = []
        for d in state["trials"]:
            t = TrialResult(trial_id=d["trial_id"], config=d["config"],
                            metrics=d["metrics"],
                            history=list(d["history"]),
                            checkpoint=(Checkpoint(d["checkpoint"])
                                        if d["checkpoint"] else None),
                            error=d["error"], status=d["status"],
                            path=d["path"])
            trials.append(t)
        tuner._restored_trials = trials
        return tuner

    # ------------------------------------------------------------------
    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self._tune_config
        scheduler = tc.scheduler or FIFOScheduler()
        run_name = self._run_config.name or f"tune_{int(time.time())}"
        storage = self._run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        exp_dir = os.path.join(storage, run_name)
        os.makedirs(exp_dir, exist_ok=True)

        searcher = tc.search_alg
        if searcher is not None:
            def _no_grid(node):
                from ray_tpu.tune.search import GridSearch
                if isinstance(node, GridSearch):
                    raise ValueError(
                        "grid_search is not supported together with "
                        "search_alg — the searcher owns the sampling")
                if isinstance(node, dict):
                    for v in node.values():
                        _no_grid(v)
            _no_grid(self._param_space)
        if self._restored_trials is not None:
            trials = self._restored_trials
            # Finished trials keep their results; everything else
            # re-runs, resuming from its last checkpoint when present.
            pending = [t for t in trials
                       if t.status not in ("TERMINATED",
                                           "EARLY_STOPPED")]
            # A searcher-driven sweep still owes the rest of its
            # num_samples budget; seed the searcher with the finished
            # trials so it resumes informed, not cold.
            remaining_suggestions = (
                max(tc.num_samples - len(trials), 0)
                if searcher is not None else 0)
            if searcher is not None:
                for t in trials:
                    if t.status in ("TERMINATED", "EARLY_STOPPED")                             and t.metrics:
                        searcher.record(t.config, t.metrics)
        elif searcher is not None:
            trials = []
            pending = []
            remaining_suggestions = max(tc.num_samples, 1)
        else:
            variants = generate_variants(self._param_space,
                                         tc.num_samples, seed=tc.seed)
            trials = [TrialResult(
                trial_id=f"trial_{i:05d}", config=v, metrics={},
                path=os.path.join(exp_dir, f"trial_{i:05d}"))
                for i, v in enumerate(variants)]
            pending = list(trials)
            remaining_suggestions = 0
        running: Dict[str, dict] = {}     # trial_id -> {actor, ref, ...}
        client = ray_tpu._ensure_connected()
        last_snapshot = 0.0

        trials_by_id = {t.trial_id: t for t in trials}
        paused: Dict[str, TrialResult] = {}
        loop_t0 = time.time()

        def _stop_hit(tid: str, metrics: Dict[str, Any]) -> bool:
            cond = getattr(self._run_config, "stop", None)
            if cond is None:
                return False
            if callable(cond):
                return bool(cond(tid, metrics))
            return any(k in metrics and metrics[k] >= v
                       for k, v in cond.items())
        pause_epochs: Dict[str, int] = {}     # resume incarnation count
        stale_ns: Dict[str, List[str]] = {}   # ns of killed incarnations
        while pending or running or paused or remaining_suggestions:
            if tc.time_budget_s is not None \
                    and time.time() - loop_t0 > tc.time_budget_s:
                # Budget exhausted: drop everything not yet running and
                # stop live trials with their last reported metrics.
                pending.clear()
                remaining_suggestions = 0
                for tid, t in list(paused.items()):
                    t.status = "TERMINATED"
                    del paused[tid]
                    for ns in stale_ns.pop(tid, []):
                        for key in client.kv_keys(ns):
                            client.kv_del(ns, key)
                    if searcher is not None and t.metrics:
                        searcher.record(t.config, t.metrics)
                for tid in list(running):
                    info = running.pop(tid)
                    info["trial"].status = "TERMINATED"
                    # Kill FIRST, then drain: a report landing between
                    # a drain and the kill would orphan in the KV
                    # forever (the race _exploit_restart documents).
                    self._stop_trial(info)
                    self._drain_final(client, info, info["trial"],
                                      scheduler)
                    for key in client.kv_keys(info["ns"]):
                        client.kv_del(info["ns"], key)
                    if searcher is not None \
                            and info["trial"].metrics:
                        searcher.record(info["trial"].config,
                                        info["trial"].metrics)
                break
            if not pending and not remaining_suggestions \
                    and hasattr(scheduler, "seal"):
                # Every trial that will ever exist is registered:
                # under-full HyperBand brackets may now release.
                scheduler.seal()
            # Synchronous schedulers (HyperBand) release paused trials
            # in batches once a rung fills.
            if hasattr(scheduler, "pop_runnable"):
                for tid, verdict in scheduler.pop_runnable().items():
                    t = paused.pop(tid, None)
                    if t is None:
                        continue

                    if verdict == "STOP":
                        t.status = "EARLY_STOPPED"
                        for ns in stale_ns.pop(tid, []):
                            for key in client.kv_keys(ns):
                                client.kv_del(ns, key)
                        if searcher is not None and t.metrics:
                            searcher.record(t.config, t.metrics)
                    else:
                        t.status = "PENDING"
                        pending.insert(0, t)
                # Liveness valve: if everything sits paused and the
                # scheduler has nothing to say (e.g. a bracket whose
                # peers all errored), resume rather than spin forever.
                if paused and not pending and not running \
                        and not remaining_suggestions:
                    for tid, t in list(paused.items()):
                        t.status = "PENDING"
                        pending.append(paused.pop(tid))
            while len(running) < tc.max_concurrent_trials:
                if pending:
                    t = pending.pop(0)
                elif remaining_suggestions:
                    cfg = searcher.suggest(self._param_space)
                    tid = f"trial_{len(trials):05d}"
                    t = TrialResult(trial_id=tid, config=cfg,
                                    metrics={},
                                    path=os.path.join(exp_dir, tid))
                    trials.append(t)
                    trials_by_id[tid] = t
                    remaining_suggestions -= 1
                else:
                    break
                os.makedirs(t.path, exist_ok=True)
                # Pause-resumed incarnations get a fresh namespace (a
                # report the dying actor landed after our drain must
                # not be consumed as if from the new run — same race
                # _exploit_restart rotates ns for) and continue the
                # iteration count from recorded history.
                p_epoch = pause_epochs.get(t.trial_id, 0)
                ns = f"tune_reports/{exp_dir}/{t.trial_id}" + (
                    f"/p{p_epoch}" if p_epoch else "")
                resume = (t.checkpoint.path
                          if t.checkpoint is not None else None)
                actor = _TrialActor.remote(t.trial_id, t.path, t.config,
                                           ns, restore_checkpoint=resume)
                ref = actor.run.remote(self._fn)
                t.status = "RUNNING"
                running[t.trial_id] = {"trial": t, "actor": actor,
                                       "ref": ref, "ns": ns,
                                       "iter": len(t.history),
                                       "epoch": 0,
                                       "old_ns": stale_ns.pop(
                                           t.trial_id, [])}
                if hasattr(scheduler, "register_trial"):
                    scheduler.register_trial(t.trial_id, t.config)
            refs = [info["ref"] for info in running.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=0.2)
            # Drain reports + scheduler decisions for every live trial.
            for tid in list(running):
                info = running[tid]
                t = info["trial"]
                stop = False
                pause = False
                exploit = None
                for key in sorted(client.kv_keys(info["ns"])):
                    blob = client.kv_get(info["ns"], key)
                    client.kv_del(info["ns"], key)
                    if blob is None or stop or pause or exploit:
                        continue   # post-decision reports don't count
                    metrics, ckpt_path = pickle.loads(blob)
                    info["iter"] += 1
                    metrics.setdefault("training_iteration",
                                       info["iter"])
                    t.history.append(metrics)
                    t.metrics = metrics
                    if ckpt_path:
                        t.checkpoint = Checkpoint(ckpt_path)
                    decision = scheduler.on_result(tid, metrics)
                    if _stop_hit(tid, metrics):
                        stop = True
                    if decision == STOP:
                        stop = True
                    elif decision == PAUSE:
                        pause = True
                    elif isinstance(decision, dict):
                        exploit = decision
                if pause and not stop:
                    # Rung checkpoint: release the slot; the scheduler
                    # resumes (or stops) the trial via pop_runnable.
                    t.status = "PAUSED"
                    self._stop_trial(info)
                    pause_epochs[tid] = pause_epochs.get(tid, 0) + 1
                    stale_ns[tid] = (info.get("old_ns") or []) \
                        + [info["ns"]]
                    del running[tid]
                    paused[tid] = t
                    continue
                if stop:
                    t.status = "EARLY_STOPPED"
                    self._stop_trial(info)
                    del running[tid]
                    if hasattr(scheduler, "on_trial_remove"):
                        # Bracket peers must not wait on a stopped
                        # trial (user stop conditions end trials the
                        # scheduler did not decide about).
                        scheduler.on_trial_remove(tid)
                    if searcher is not None and t.metrics:
                        searcher.record(t.config, t.metrics)
                elif exploit is not None:
                    src = trials_by_id.get(exploit["source"])
                    if src is None or src.checkpoint is None:
                        continue      # nothing to clone yet; skip
                    self._exploit_restart(info, t, src,
                                          exploit["config"], scheduler,
                                          exp_dir)
            # Reap finished trials.
            done_refs = set(r.binary() for r in ready)
            for tid in list(running):
                info = running[tid]
                if info["ref"].binary() not in done_refs:
                    continue
                t = info["trial"]
                try:
                    tb = ray_tpu.get(info["ref"])
                    if tb is None:
                        t.status = "TERMINATED"
                    else:
                        t.status = "ERROR"
                        t.error = tb
                except (exc.ActorDiedError,
                        exc.WorkerCrashedError) as e:
                    t.status = "ERROR"
                    t.error = str(e)
                self._drain_final(client, info, t, scheduler)
                self._stop_trial(info)
                del running[tid]
                if hasattr(scheduler, "on_trial_remove"):
                    # Bracket peers must not wait on a finished trial.
                    scheduler.on_trial_remove(tid)
                # Only completed runs inform the model: an ERROR
                # trial's last metric never finished.
                if searcher is not None and t.status == "TERMINATED" \
                        and t.metrics:
                    searcher.record(t.config, t.metrics)
            now = time.time()
            if now - last_snapshot > 1.0:
                last_snapshot = now
                try:
                    self._save_experiment_state(exp_dir, trials)
                except Exception:
                    pass
        self._save_experiment_state(exp_dir, trials)
        return ResultGrid(trials)

    @staticmethod
    def _drain_final(client, info, t: TrialResult, scheduler) -> None:
        for ns in info.get("old_ns", []):
            for key in client.kv_keys(ns):   # orphaned pre-exploit ns
                client.kv_del(ns, key)
        for key in sorted(client.kv_keys(info["ns"])):
            blob = client.kv_get(info["ns"], key)
            client.kv_del(info["ns"], key)
            if blob is None:
                continue
            metrics, ckpt_path = pickle.loads(blob)
            info["iter"] += 1
            metrics.setdefault("training_iteration", info["iter"])
            t.history.append(metrics)
            t.metrics = metrics
            if ckpt_path:
                t.checkpoint = Checkpoint(ckpt_path)

    def _exploit_restart(self, info: dict, t: TrialResult,
                         src: TrialResult, new_config: Dict[str, Any],
                         scheduler, exp_dir: str) -> None:
        """PBT exploit: kill the trial's actor and restart it from the
        source trial's checkpoint with the mutated config (reference:
        pbt.py _exploit — checkpoint clone + explore)."""
        self._stop_trial(info)
        t.config = dict(new_config)
        info["epoch"] += 1
        # The old actor may land a report between our drain and the
        # kill; remember its namespace so the final sweep deletes those
        # orphans instead of leaking them in the GCS forever.
        info.setdefault("old_ns", []).append(info["ns"])
        ns = (f"tune_reports/{exp_dir}/{t.trial_id}"
              f"/e{info['epoch']}")
        actor = _TrialActor.remote(
            t.trial_id, t.path, t.config, ns,
            restore_checkpoint=src.checkpoint.path)
        info["actor"] = actor
        info["ref"] = actor.run.remote(self._fn)
        info["ns"] = ns
        if hasattr(scheduler, "register_trial"):
            scheduler.register_trial(t.trial_id, t.config)

    @staticmethod
    def _stop_trial(info: dict) -> None:
        try:
            ray_tpu.kill(info["actor"])
        except Exception:
            pass


def _trainer_trainable(trainer) -> Callable:
    """Wrap a TpuTrainer so each trial runs trainer.fit() with the
    variant's train_loop_config merged (reference:
    base_trainer.py:693-724)."""

    def run_trainer(config: Dict[str, Any]) -> None:
        import copy
        from ray_tpu.train import session
        t = copy.copy(trainer)
        merged = dict(t._config or {})
        merged.update(config.get("train_loop_config", config))
        t._config = merged
        ctx = session.get_context()
        # Nest the inner run's outputs under this trial's directory.
        from ray_tpu.train.trainer import RunConfig
        rc = t._run_config
        t._run_config = RunConfig(
            name="train", storage_path=ctx.get_trial_dir(),
            failure_config=rc.failure_config,
            checkpoint_config=rc.checkpoint_config)
        result = t.fit()
        if result.error is not None:
            raise result.error
        session.report(dict(result.metrics, _train_done=1),
                       checkpoint=result.checkpoint)

    return run_trainer


def with_parameters(fn: Callable, **large_objects) -> Callable:
    """Attach large constant objects to a trainable WITHOUT copying
    them into every trial's pickled closure (reference:
    tune.with_parameters): each object is `put` into the object store
    ONCE; trials resolve the shared refs at start.

        tuner = Tuner(with_parameters(train, data=big_df),
                      param_space=...)
        # train(config, data=...) sees the materialized object.
    """
    refs = {k: ray_tpu.put(v) for k, v in large_objects.items()}

    def wrapped(config):
        keys = list(refs)
        vals = ray_tpu.get([refs[k] for k in keys])   # one batched get
        return fn(config, **dict(zip(keys, vals)))

    wrapped.__name__ = getattr(fn, "__name__", "trainable")
    return wrapped
