"""Search spaces + basic variant generation.

Analog of the reference's tune search-space API (tune/search/sample.py:
uniform/loguniform/randint/choice, tune/search/variant_generator.py
grid expansion): `grid_search` values cross-product; distribution
objects are sampled per trial by the BasicVariantGenerator.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float) -> None:
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float) -> None:
        import math
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int) -> None:
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options: Sequence[Any]) -> None:
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    def __init__(self, values: Sequence[Any]) -> None:
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(options)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Cross-product of every grid_search axis, x num_samples, with
    distribution leaves re-sampled per variant (reference:
    BasicVariantGenerator semantics)."""
    rng = random.Random(seed)
    grid_paths: List[tuple] = []
    grid_values: List[List[Any]] = []

    def find_grids(node, path):
        if isinstance(node, GridSearch):
            grid_paths.append(path)
            grid_values.append(node.values)
        elif isinstance(node, dict):
            for k, v in node.items():
                find_grids(v, path + (k,))

    find_grids(param_space, ())

    def build(node, path, grid_assign):
        if isinstance(node, GridSearch):
            return grid_assign[path]
        if isinstance(node, Domain):
            return node.sample(rng)
        if isinstance(node, dict):
            return {k: build(v, path + (k,), grid_assign)
                    for k, v in node.items()}
        return node

    combos = (list(itertools.product(*grid_values))
              if grid_values else [()])
    variants = []
    for _ in range(max(num_samples, 1)):
        for combo in combos:
            assign = dict(zip(grid_paths, combo))
            variants.append(build(param_space, (), assign))
    return variants


# ---------------------------------------------------------------------------
# Model-based search: TPE (reference: tune/search/optuna/optuna_search.py
# wraps Optuna's TPE sampler; here the estimator is native).
# ---------------------------------------------------------------------------
def _flatten(space: Dict[str, Any], path=()):  # leaves that are Domains
    for k, v in space.items():
        if isinstance(v, Domain):
            yield path + (k,), v
        elif isinstance(v, dict):
            yield from _flatten(v, path + (k,))


def _get(cfg, path):
    for k in path:
        cfg = cfg[k]
    return cfg


def _vals(cfgs, path):
    out = []
    for c in cfgs:
        try:
            out.append(_get(c, path))
        except (KeyError, TypeError):
            pass   # config from an older param space
    return out


def _has(cfg, path) -> bool:
    try:
        _get(cfg, path)
        return True
    except (KeyError, TypeError):
        return False


def _set(cfg, path, value):
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


class TPESearcher:
    """Tree-structured Parzen Estimator-style searcher.

    After `n_startup` random trials, observations split into good/bad
    by the `gamma` quantile of the objective; candidates are sampled by
    perturbing good configurations and ranked by a kernel density
    ratio l(x)/g(x) (good-density over bad-density) in each numeric
    domain's transformed space.  Plugs into TuneConfig(search_alg=...);
    the Tuner calls suggest() per trial and record() per completion.
    """

    def __init__(self, metric: str, mode: str = "max",
                 n_startup: int = 5, gamma: float = 0.25,
                 n_candidates: int = 32, seed: int = 0) -> None:
        if mode not in ("max", "min"):
            raise ValueError(f"mode must be max|min, got {mode!r}")
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._obs: List[tuple] = []       # (config, score)

    # -- observation -----------------------------------------------------
    def record(self, config: Dict[str, Any],
               metrics: Dict[str, Any]) -> None:
        if self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((config, score))

    # -- suggestion ------------------------------------------------------
    def _random(self, space: Dict[str, Any]) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        for path, dom in _flatten(space):
            _set(cfg, path, dom.sample(self._rng))
        # constants pass through
        def fill(node, out):
            for k, v in node.items():
                if isinstance(v, dict):
                    fill(v, out.setdefault(k, {}))
                elif not isinstance(v, Domain):
                    out[k] = v
        fill(space, cfg)
        return cfg

    @staticmethod
    def _warp(dom: Domain, value):
        import math
        if isinstance(dom, LogUniform):
            return math.log(value)
        return float(value) if isinstance(dom, (Uniform, RandInt)) \
            else value

    @classmethod
    def _safe_warp(cls, dom: Domain, value):
        """None when a legacy value no longer fits the domain (restored
        sweeps may carry configs from an older param space)."""
        try:
            return cls._warp(dom, value)
        except (TypeError, ValueError):
            return None

    def _density(self, dom: Domain, pts: List[Any], x) -> float:
        """Parzen window density of x under the point set (numeric
        domains: gaussian kernels; categorical: smoothed counts)."""
        import math
        if isinstance(dom, Choice) or not pts:
            n = len(pts) or 1
            hits = sum(1 for p in pts if p == x)
            return (hits + 0.5) / (n + 0.5 * max(len(getattr(
                dom, "options", [1])), 1))
        xs = [w for p in pts
              if (w := self._safe_warp(dom, p)) is not None]
        xv = self._safe_warp(dom, x)
        if xv is None or not xs:
            return 1e-12
        spread = (max(xs) - min(xs)) or 1.0
        h = max(spread / max(len(xs) ** 0.5, 1.0), 1e-3)
        return sum(math.exp(-0.5 * ((xv - p) / h) ** 2)
                   for p in xs) / (len(xs) * h)

    def suggest(self, space: Dict[str, Any]) -> Dict[str, Any]:
        if len(self._obs) < self.n_startup:
            return self._random(space)
        return self._suggest_from(self._obs, space)

    def _suggest_from(self, obs: List[tuple],
                      space: Dict[str, Any]) -> Dict[str, Any]:
        ranked = sorted(obs, key=lambda t: -t[1])
        n_good = max(1, int(len(ranked) * self.gamma))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        domains = list(_flatten(space))
        # Per-path observation values, extracted ONCE per suggest()
        # (not per candidate).
        good_vals = {path: _vals(good, path) for path, _ in domains}
        bad_vals = {path: _vals(bad, path) for path, _ in domains}
        best_cfg, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            cand = self._random(space)
            # Perturb toward a good point: half the time the single
            # best observation (exploitation), otherwise a random good
            # point (diversity).
            anchor = (good[0] if self._rng.random() < 0.5
                      else self._rng.choice(good))
            for path, dom in domains:
                if self._rng.random() < 0.8:
                    try:
                        av = _get(anchor, path)
                    except (KeyError, TypeError):
                        continue
                    if isinstance(dom, Choice):
                        _set(cand, path, av)
                    elif isinstance(dom, RandInt):
                        lo, hi = dom.low, dom.high
                        width = max((hi - lo) // 5, 1)
                        _set(cand, path, max(lo, min(
                            hi - 1, av + self._rng.randint(-width,
                                                           width))))
                    else:
                        import math
                        w = self._warp(dom, av)
                        # Self-tightening bandwidth (classic TPE): the
                        # kernel width tracks the good set's spread, so
                        # exploitation sharpens as evidence accumulates.
                        gv = [w for c in good if _has(c, path)
                              and (w := self._safe_warp(
                                  dom, _get(c, path))) is not None]
                        if isinstance(dom, LogUniform):
                            span = (dom._hi - dom._lo) or 1.0
                            lo, hi = dom._lo, dom._hi
                        else:
                            span = (dom.high - dom.low) or 1.0
                            lo, hi = dom.low, dom.high
                        spread = ((max(gv) - min(gv))
                                  if len(gv) > 1 else span)
                        # Annealed floor: wide early (escape local
                        # clusters), tightening as evidence accumulates
                        # so late trials refine instead of wandering.
                        floor = span / (8.0 + len(obs) / 2.0)
                        sigma = max(spread / max(len(gv), 1) ** 0.5,
                                    floor)
                        w += self._rng.gauss(0, sigma)
                        w = max(lo, min(hi, w))
                        _set(cand, path,
                             math.exp(w) if isinstance(dom, LogUniform)
                             else w)
            ratio = 1.0
            for path, dom in domains:
                x = _get(cand, path)
                lg = self._density(dom, good_vals[path], x)
                lb = self._density(dom, bad_vals[path], x)
                ratio *= (lg + 1e-12) / (lb + 1e-12)
            # Novelty factor: pure density-ratio argmax re-evaluates the
            # good cluster's center forever (measured); weighting by
            # distance to the nearest ALREADY-EVALUATED point pushes
            # suggestions to the cluster's rim, which is what actually
            # drags the good set toward the optimum.
            novelty = 1.0
            for path, dom in domains:
                if isinstance(dom, Choice):
                    continue
                xv = self._warp(dom, _get(cand, path))
                if isinstance(dom, LogUniform):
                    span = (dom._hi - dom._lo) or 1.0
                else:
                    span = (dom.high - dom.low) or 1.0
                dmin = min((abs(xv - w) for c, _ in obs
                            if _has(c, path)
                            and (w := self._safe_warp(
                                dom, _get(c, path))) is not None),
                           default=span)
                scale = span / (8.0 + len(obs) / 2.0)
                novelty *= min(dmin / scale, 1.0) + 0.05
            ratio *= novelty
            if ratio > best_ratio:
                best_ratio, best_cfg = ratio, cand
        return best_cfg if best_cfg is not None else self._random(space)


class BOHBSearcher(TPESearcher):
    """BOHB-style budget-aware model-based search (reference:
    tune/search/bohb/bohb_search.py TuneBOHB paired with
    tune/schedulers/hb_bohb.py HyperBandForBOHB).

    The BOHB rule (Falkner et al., ICML'18): observations are grouped
    by the budget they were measured at (`time_attr`, i.e. the ASHA
    rung a trial reached before being stopped or finishing), and the
    TPE good/bad density model is fitted on the LARGEST budget that has
    at least `min_points` observations.  Cheap low-rung results guide
    the model early; as full-budget results accumulate they take over.
    Scores from different budgets are never mixed into one model —
    that's the part plain TPE gets wrong under early stopping.

    Pair with ASHAScheduler over the same `time_attr`:

        TuneConfig(search_alg=BOHBSearcher("loss", mode="min"),
                   scheduler=ASHAScheduler("loss", mode="min"))
    """

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 min_points: int = 6, n_startup: int = 5,
                 gamma: float = 0.25, n_candidates: int = 32,
                 seed: int = 0) -> None:
        super().__init__(metric, mode, n_startup=n_startup, gamma=gamma,
                         n_candidates=n_candidates, seed=seed)
        self.time_attr = time_attr
        self.min_points = min_points
        self._by_budget: Dict[int, List[tuple]] = {}

    def record(self, config: Dict[str, Any],
               metrics: Dict[str, Any]) -> None:
        if self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "min":
            score = -score
        budget = int(metrics.get(self.time_attr, 0))
        self._by_budget.setdefault(budget, []).append((config, score))
        self._obs.append((config, score))   # drives n_startup gate only

    def suggest(self, space: Dict[str, Any]) -> Dict[str, Any]:
        if len(self._obs) < self.n_startup:
            return self._random(space)
        eligible = [b for b, o in self._by_budget.items()
                    if len(o) >= self.min_points]
        if not eligible:
            # Not enough points at any single budget yet: model the
            # most-populated budget rather than mixing scales.
            budget = max(self._by_budget,
                         key=lambda b: (len(self._by_budget[b]), b))
        else:
            budget = max(eligible)
        return self._suggest_from(self._by_budget[budget], space)


def _freeze(obj):
    """Deterministic hashable key for a (possibly nested) config."""
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    return obj


class ExternalSearcher:
    """Generic ask-tell adapter: plug ANY external optimizer into Tune.

    The reference wraps each library separately (Optuna at
    tune/search/optuna/optuna_search.py:79, HyperOpt, Ax, HEBO,
    Nevergrad — one adapter class each); this single seam covers the
    whole category: the user supplies

        ask(param_space) -> config  |  (config, handle)
        tell(handle_or_config, score) -> None        (optional)

    and the adapter does the bookkeeping Tune needs: it extracts the
    objective from reported metrics, flips the sign so the external
    optimizer always sees a MAXIMIZATION problem (``mode="min"``
    negates), and routes each completion back to the ask() that
    produced it (configs are keyed structurally, so duplicate configs
    resolve FIFO to their own handles).

    Optuna example (works with any study — see ``from_optuna``)::

        study = optuna.create_study(direction="maximize")
        searcher = ExternalSearcher.from_optuna(
            study,
            lambda trial: {"lr": trial.suggest_float(
                "lr", 1e-5, 1e-1, log=True)},
            metric="acc")
        Tuner(train_fn, param_space={},  # space lives in suggest_fn
              tune_config=TuneConfig(search_alg=searcher,
                                     num_samples=20)).fit()
    """

    def __init__(self, ask, tell=None, metric: str = "score",
                 mode: str = "max") -> None:
        if mode not in ("max", "min"):
            raise ValueError(f"mode must be max|min, got {mode!r}")
        self.ask = ask
        self.tell = tell
        self.metric = metric
        self.mode = mode
        self._handles: Dict[Any, List[Any]] = {}

    # -- Tune searcher contract (same as TPESearcher) -------------------
    def suggest(self, space: Dict[str, Any]) -> Dict[str, Any]:
        out = self.ask(space)
        if isinstance(out, tuple) and len(out) == 2:
            config, handle = out
        else:
            config, handle = out, None
        if handle is not None:
            self._handles.setdefault(_freeze(config), []).append(handle)
        return config

    def record(self, config: Dict[str, Any],
               metrics: Dict[str, Any]) -> None:
        if self.tell is None or self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "min":
            score = -score
        handles = self._handles.get(_freeze(config))
        handle = handles.pop(0) if handles else config
        try:
            self.tell(handle, score)
        except Exception:
            # An external optimizer that rejects a duplicate/stale
            # report must not kill the sweep loop.
            pass

    @classmethod
    def from_optuna(cls, study, suggest_fn, metric: str,
                    mode: str = "max") -> "ExternalSearcher":
        """Adapter over an optuna Study: ``suggest_fn(trial) -> config``
        defines the space via optuna's native suggest_* calls."""

        def ask(_space):
            trial = study.ask()
            return suggest_fn(trial), trial

        def tell(handle, score):
            study.tell(handle, score)

        return cls(ask, tell, metric=metric, mode=mode)
