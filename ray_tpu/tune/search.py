"""Search spaces + basic variant generation.

Analog of the reference's tune search-space API (tune/search/sample.py:
uniform/loguniform/randint/choice, tune/search/variant_generator.py
grid expansion): `grid_search` values cross-product; distribution
objects are sampled per trial by the BasicVariantGenerator.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float) -> None:
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float) -> None:
        import math
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int) -> None:
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options: Sequence[Any]) -> None:
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    def __init__(self, values: Sequence[Any]) -> None:
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(options)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Cross-product of every grid_search axis, x num_samples, with
    distribution leaves re-sampled per variant (reference:
    BasicVariantGenerator semantics)."""
    rng = random.Random(seed)
    grid_paths: List[tuple] = []
    grid_values: List[List[Any]] = []

    def find_grids(node, path):
        if isinstance(node, GridSearch):
            grid_paths.append(path)
            grid_values.append(node.values)
        elif isinstance(node, dict):
            for k, v in node.items():
                find_grids(v, path + (k,))

    find_grids(param_space, ())

    def build(node, path, grid_assign):
        if isinstance(node, GridSearch):
            return grid_assign[path]
        if isinstance(node, Domain):
            return node.sample(rng)
        if isinstance(node, dict):
            return {k: build(v, path + (k,), grid_assign)
                    for k, v in node.items()}
        return node

    combos = (list(itertools.product(*grid_values))
              if grid_values else [()])
    variants = []
    for _ in range(max(num_samples, 1)):
        for combo in combos:
            assign = dict(zip(grid_paths, combo))
            variants.append(build(param_space, (), assign))
    return variants
