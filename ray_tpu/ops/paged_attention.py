"""Ragged paged attention: decode-time attention over a paged KV cache.

The serving engine (serve/llm.py PagedBatcher) stores KV in fixed-size
blocks from a shared pool instead of one dense [B, M, ...] slab per
slot; each request owns a *block table* mapping its logical block index
to a physical pool block.  Blocks are refcount-shared, so requests with
a common prompt prefix attend the SAME physical prefix blocks (the
radix/prefix cache) — this kernel is what makes that sharing free at
decode time ("Ragged Paged Attention: A High-Performance and Flexible
LLM Inference Kernel for TPU", PAPERS.md).

Two implementations behind one dispatcher:

* `paged_attention_reference` — pure JAX (`jnp.take` gather through the
  block table + masked softmax), runs everywhere and is the numerics
  oracle the CPU tier-1 suite exercises.  Mathematically identical to
  the dense decode attention in models/decoding.py (_gqa_scores +
  length mask), just addressed through the table.
* `_paged_fwd` — a Pallas TPU kernel following ops/attention.py's
  flash structure: online softmax accumulated block-by-block, with the
  block table passed as a SCALAR-PREFETCH argument so the kv BlockSpec
  index_map gathers physical blocks directly (no materialized [B, M]
  window in HBM).  The grid is (B, Hkv, W); blocks past a sequence's
  context length are skipped with `pl.when` — that is the "ragged"
  part: compute scales with the tokens actually cached, not with the
  table width.

Shapes (decode: ONE query token per sequence):
  q:            [B, H, D]
  k_pool/v_pool [NB, bs, Hkv, D]   (one layer's pool)
  block_tables  [B, W] int32        (physical block per logical block)
  context_lens  [B]    int32        (valid positions, INCLUSIVE of the
                                     token scattered this step)
  -> out        [B, H, D]

Pool block 0 is reserved as a scratch/null block by the engine (table
padding and retired-slot writes are redirected there), so garbage reads
through padded table entries are always masked by context_lens.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Reference implementation (works everywhere; the numerics oracle)
# ---------------------------------------------------------------------------
def paged_attention_reference(q: jax.Array, k_pool: jax.Array,
                              v_pool: jax.Array, block_tables: jax.Array,
                              context_lens: jax.Array,
                              scale: Optional[float] = None) -> jax.Array:
    """Gather-based paged attention (the CPU/tier-1 path).

    Gathers each sequence's blocks into a [B, W*bs, Hkv, D] window with
    `jnp.take`, then runs exactly the dense decode attention math:
    f32 scores, -inf mask beyond context_lens, softmax, f32 weighted
    sum — so paged decode matches dense `decode_step` numerics.
    """
    B, H, D = q.shape
    bs, hkv = k_pool.shape[1], k_pool.shape[2]
    W = block_tables.shape[1]
    M = W * bs
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    # [B, W, bs, Hkv, D] -> [B, M, Hkv, D]
    k = jnp.take(k_pool, block_tables, axis=0).reshape(B, M, hkv, D)
    v = jnp.take(v_pool, block_tables, axis=0).reshape(B, M, hkv, D)
    groups = H // hkv
    qg = q.reshape(B, hkv, groups, D)
    s = jnp.einsum("bhgk,bmhk->bhgm", qg.astype(jnp.float32),
                   k.astype(jnp.float32)).reshape(B, H, M) * scale
    mask = jnp.arange(M)[None, :] < context_lens[:, None]      # [B, M]
    s = jnp.where(mask[:, None, :], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    # A zero-length row's softmax is all-NaN (every score -inf); the
    # kernel's l==0 guard returns zeros there — match it so both
    # impls stay interchangeable for padded/inactive rows.
    w = jnp.where(mask[:, None, :], w, 0.0)
    wg = w.reshape(B, hkv, groups, M)
    o = jnp.einsum("bhgm,bmhk->bhgk", wg, v.astype(jnp.float32))
    return o.reshape(B, H, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------
pl = None
pltpu = None


def _ensure_pallas():
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu
        pl = _pl
        pltpu = _pltpu


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, scale, block_size):
    """One (sequence, kv-head, logical-block) program.

    bt_ref/len_ref are scalar-prefetch refs (the block table routed the
    kv BlockSpecs here before the body ran); the body only masks and
    accumulates.  Transposed orientation like ops/attention.py: scores
    are (bs, G) so per-query stats stay lane-aligned row vectors.
    """
    b = pl.program_id(0)
    w = pl.program_id(2)
    nw = pl.num_programs(2)
    ctx = len_ref[b]

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(w * block_size < ctx)            # ragged: skip dead blocks
    def _body():
        q = q_ref[0, 0]                        # (G, D)
        k = k_ref[0, :, 0]                     # (bs, D)
        s_T = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bs, G)
        kpos = w * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s_T.shape, 0)
        s_T = jnp.where(kpos < ctx, s_T, NEG_INF)
        m_prev = m_ref[...]                    # (1, G)
        l_prev = l_ref[...]
        m_cur = jnp.max(s_T, axis=0, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p_T = jnp.exp(s_T - m_new)             # (bs, G)
        l_ref[...] = alpha * l_prev + jnp.sum(p_T, axis=0, keepdims=True)
        m_ref[...] = m_new
        v_blk = v_ref[0, :, 0]                 # (bs, D)
        # acc (G, D) = alpha * acc + p_T^T @ v
        acc_ref[...] = acc_ref[...] * alpha[0][:, None] + \
            jax.lax.dot_general(
                p_T, v_blk.astype(jnp.float32),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(w == nw - 1)
    def _finish():
        l = l_ref[...][0]                      # (G,)
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _paged_fwd(q, k_pool, v_pool, block_tables, context_lens, scale,
               interpret):
    _ensure_pallas()
    B, H, D = q.shape
    nb, bs, hkv = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    W = block_tables.shape[1]
    groups = H // hkv
    qg = q.reshape(B, hkv, groups, D)

    # Scalar-prefetch index maps: grid indices first, then the
    # prefetched refs — the kv specs dereference the block table.
    def kv_index(b, h, w, bt_ref, len_ref):
        return (bt_ref[b, w], 0, h, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, hkv, W),
        in_specs=[
            pl.BlockSpec((1, 1, groups, D),
                         lambda b, h, w, bt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, bs, 1, D), kv_index),
            pl.BlockSpec((1, bs, 1, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, groups, D),
                               lambda b, h, w, bt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((groups, D), jnp.float32),
            pltpu.VMEM((1, groups), jnp.float32),
            pltpu.VMEM((1, groups), jnp.float32),
        ],
    )
    o = pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, block_size=bs),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, hkv, groups, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      qg, k_pool, v_pool)
    return o.reshape(B, H, D)


def paged_attention_kernel(q, k_pool, v_pool, block_tables, context_lens,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Pallas paged attention (interpret-mode off-TPU for parity tests)."""
    D = q.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    return _paged_fwd(q, k_pool, v_pool, block_tables, context_lens,
                      scale, interpret)


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    block_tables: jax.Array, context_lens: jax.Array,
                    scale: Optional[float] = None,
                    impl: str = "auto") -> jax.Array:
    """Dispatcher: Pallas kernel on TPU, gather reference elsewhere.

    Decode has no backward pass, so there is no custom VJP — the
    reference path stays differentiable by construction if anyone ever
    scores with it.
    """
    if impl == "reference":
        return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                         context_lens, scale)
    if impl == "kernel":
        return paged_attention_kernel(q, k_pool, v_pool, block_tables,
                                      context_lens, scale)
    on_tpu = any(dev.platform == "tpu" for dev in jax.devices())
    if on_tpu and q.shape[2] % 64 == 0 and q.shape[1] % k_pool.shape[2] == 0:
        return paged_attention_kernel(q, k_pool, v_pool, block_tables,
                                      context_lens, scale)
    return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                     context_lens, scale)
