"""Ring attention: sequence/context parallelism over an ICI ring.

Not present in the reference (SURVEY.md §2.3: no ring attention, Ulysses
or context parallel anywhere in-tree) — this is new, first-class
capability.  Design (Liu et al. ring attention, blockwise formulation):

* The sequence axis is sharded over mesh axis `sp`; every device holds a
  [B, H, S/n, D] shard of q, k, v.
* Step 0 computes the diagonal block (local q vs local kv, causal mask).
  Then n-1 ring steps: rotate k/v to the next neighbor with
  `jax.lax.ppermute` (XLA lowers to ICI neighbor exchanges overlapped
  with compute) and attend the incoming shard.
* Each step produces a NORMALIZED partial (o_t, lse_t); partials merge
  with the logsumexp rule  lse = logaddexp(lse_a, lse_b),
  o = o_a·e^(lse_a-lse) + o_b·e^(lse_b-lse)  — numerics match exact
  attention.
* Causality across shards is static per step kind: the diagonal step
  runs the causal kernel; rotated steps run the non-causal kernel and a
  future shard's contribution is nullified by setting its lse to -inf
  (SPMD lockstep — every device executes the same program).

The per-step attention uses the pallas flash kernel (with lse output,
differentiable via its custom VJP) when shapes tile on TPU; otherwise
the einsum reference path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import (NEG_INF, attention_reference_with_lse,
                                   flash_attention_with_lse)


def _partial_attn(q, k, v, scale, causal):
    """(o, lse) for one kv shard; flash kernel when tileable on TPU."""
    sq, sk, d = q.shape[2], k.shape[2], q.shape[3]
    tileable = (sq % 128 == 0 and sk % 128 == 0 and d % 64 == 0
                and q.shape[1] % k.shape[1] == 0)
    if tileable and jax.default_backend() == "tpu":
        # save_residuals=False: per-step partials must NOT be tagged
        # remat-saveable — the dots policy would save all R ring steps'
        # partial o/lse instead of only the final combined output.
        return flash_attention_with_lse(q, k, v, causal=causal,
                                        scale=scale, save_residuals=False)
    return attention_reference_with_lse(q, k, v, causal=causal,
                                        scale=scale)


def _merge(o_a, lse_a, o_b, lse_b):
    """Combine two normalized partial attentions (logsumexp weights)."""
    lse = jnp.maximum(lse_a, lse_b)
    # Guard -inf - -inf (a fully-masked pair) => weight 0.
    w_a = jnp.exp(jnp.where(lse_a == NEG_INF, NEG_INF, lse_a - lse))
    w_b = jnp.exp(jnp.where(lse_b == NEG_INF, NEG_INF, lse_b - lse))
    norm = w_a + w_b
    norm = jnp.where(norm == 0.0, 1.0, norm)
    o = (o_a.astype(jnp.float32) * w_a[..., None] +
         o_b.astype(jnp.float32) * w_b[..., None]) / norm[..., None]
    lse_out = lse + jnp.log(norm)
    return o.astype(o_a.dtype), lse_out


def _ring_attention_sharded(q, k, v, *, axis_name, causal, scale):
    """Per-shard body (runs inside shard_map)."""
    n = jax.lax.psum(1, axis_name)
    r = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Step 0: diagonal block — statically causal.
    o_run, lse_run = _partial_attn(q, k, v, scale, causal=causal)
    o_run = o_run.astype(jnp.float32)

    def step(t, carry):
        o_run, lse_run, k_t, v_t = carry
        # Rotate first: after t rotations this device holds the shard
        # originating from rank (r - t) mod n.
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        src = (r - t) % n
        o_p, lse_p = _partial_attn(q, k_t, v_t, scale, causal=False)
        if causal:
            # Future shard => nullify its contribution via lse = -inf.
            lse_p = jnp.where(src < r, lse_p, NEG_INF)
        o_new, lse_new = _merge(o_run, lse_run, o_p, lse_p)
        return o_new.astype(jnp.float32), lse_new, k_t, v_t

    if n > 1:
        o_run, lse_run, _, _ = jax.lax.fori_loop(
            1, n, step, (o_run, lse_run, k, v))
    return o_run.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Sequence-parallel attention over `axis_name` of `mesh`.

    q/k/v: [B, H, S, D] GLOBAL arrays whose S dim is (to be) sharded over
    `axis_name`.  Returns [B, H, S, D] sharded the same way.
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map           # jax >= 0.8
    except ImportError:                     # pragma: no cover
        from jax.experimental.shard_map import shard_map

    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
