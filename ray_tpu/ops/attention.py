"""Fused attention: pallas TPU flash-attention kernels + reference impl.

The reference framework has NO attention kernels (it orchestrates external
libs; SURVEY.md §2.3 — sequence parallel/ring attention absent).  This is
new TPU-first capability: a blocked online-softmax attention (forward and
backward as pallas kernels, custom VJP) designed around the MXU (128-lane
tiles, f32 accumulation, bf16 inputs) and VMEM residency of one tile at a
time.

Kernel orientation: scores are computed TRANSPOSED, s_T = k @ q^T of shape
(block_k, block_q), so that all per-query statistics (running max m,
normalizer l, logsumexp, delta) are lane-aligned row vectors (1, block_q)
— TPU vectors must keep the 128-wide lane dim last, and this layout makes
every softmax/rescale a broadcast along sublanes with zero in-kernel
transposes.  The attention output accumulates as (head_dim, block_q) and
is swapped back to [.., S, D] once, outside the kernel, by XLA.

GQA is expressed in the kv BlockSpec index_map (kv head = q head //
group): grouped q heads read the same kv tiles, nothing is materialized.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

# 512-tile blocks: measured on v5e (B=8,H=12,S=1024,D=64, causal), the
# 12-layer fwd+bwd attention stack drops from 111ms (128x128 grid of 6144
# tiny programs, overhead-bound) to 52ms — identical to the stock
# jax.experimental pallas flash kernel at the same block sizes.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Reference implementation (works everywhere; the numerics oracle)
# ---------------------------------------------------------------------------
def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """q: [B, Hq, Sq, D]; k, v: [B, Hkv, Sk, D]; GQA when Hq > Hkv."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    group = hq // hkv
    qg = q.reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32))
    return o.reshape(b, hq, sq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels (transposed orientation — see module docstring)
# ---------------------------------------------------------------------------
def _causal_mask_T(qi, ki, block_q, block_k, offset):
    """mask_T[j, i] = query (qi*bq + i) may attend key (ki*bk + j).

    `offset` = sk - sq aligns the causal triangle bottom-right (the
    reference oracle's tril(k=sk-sq) convention) so cross-length causal
    attention (prefill with cache, sq < sk) is correct."""
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0)
    qpos = offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1)
    return qpos >= kpos


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal,
                block_q, block_k, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    run = (ki * block_k <= qi * block_q + block_q - 1 + offset) \
        if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0]                               # (bq, D)
        k = k_ref[0]                               # (bk, D)
        s_T = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bk, bq)
        if causal:
            s_T = jnp.where(
                _causal_mask_T(qi, ki, block_q, block_k, offset),
                s_T, NEG_INF)
        m_prev = m_ref[...]                        # (8, bq), rows equal
        l_prev = l_ref[...]
        m_cur = jnp.max(s_T, axis=0, keepdims=True)   # (1, bq)
        m_new = jnp.maximum(m_prev, m_cur)            # (8, bq)
        alpha = jnp.exp(m_prev - m_new)
        p_T = jnp.exp(s_T - m_new[0:1])               # (bk, bq)
        l_ref[...] = alpha * l_prev + jnp.sum(p_T, axis=0, keepdims=True)
        m_ref[...] = m_new
        v_blk = v_ref[0]                           # (bk, D)
        # acc_T (D, bq) += v^T @ p_T
        acc_ref[...] = acc_ref[...] * alpha[0:1] + jax.lax.dot_general(
            v_blk, p_T.astype(v_blk.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        ki_last = jnp.clip(
            (qi * block_q + block_q - 1 + offset) // block_k, 0, nk - 1)
    else:
        ki_last = nk - 1

    @pl.when(ki == ki_last)
    def _finish():
        l = l_ref[...][0:1]                        # (1, bq)
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)   # (D, bq)
        lse_ref[0] = (m_ref[...][0:1] + jnp.log(l))          # (1, bq)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                     block_q, block_k, offset):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    if causal:
        # First query block that can see this key block (offset-aligned);
        # clipped so _init always fires even for key blocks nobody sees
        # (their accumulators must be written as zeros, not stale VMEM).
        qi_first = jnp.clip((ki * block_k - offset) // block_q, 0, nq - 1)
        run = qi * block_q + block_q - 1 + offset >= ki * block_k
    else:
        qi_first = 0
        run = True

    @pl.when(qi == qi_first)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]                              # (bq, D)
        lse = lse_ref[0][0:1]                       # (1, bq)
        delta = delta_ref[0][0:1]                   # (1, bq)
        s_T = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bk, bq)
        if causal:
            s_T = jnp.where(
                _causal_mask_T(qi, ki, block_q, block_k, offset),
                s_T, NEG_INF)
        p_T = jnp.exp(s_T - lse)                    # (bk, bq)
        # dv (bk, D) += p_T @ do
        dv_acc[...] += jax.lax.dot_general(
            p_T.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp_T (bk, bq) = v @ do^T
        dp_T = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_T = p_T * (dp_T - delta) * scale
        # dk (bk, D) += ds_T @ q
        dk_acc[...] += jax.lax.dot_general(
            ds_T.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k,
                   offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1 + offset
        ki_last = jnp.clip(
            (qi * block_q + block_q - 1 + offset) // block_k, 0, nk - 1)
    else:
        run = True
        ki_last = nk - 1

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][0:1]
        delta = delta_ref[0][0:1]
        s_T = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s_T = jnp.where(
                _causal_mask_T(qi, ki, block_q, block_k, offset),
                s_T, NEG_INF)
        p_T = jnp.exp(s_T - lse)
        dp_T = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_T = p_T * (dp_T - delta) * scale
        # dq (bq, D) += ds_T^T @ k  (contract the bk dim of both)
        dq_acc[...] += jax.lax.dot_general(
            ds_T.astype(k.dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == ki_last)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------
pl = None
pltpu = None


def _ensure_pallas():
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu
        pl = _pl
        pltpu = _pltpu


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, group):
    _ensure_pallas()
    bh, sq, d = q.shape
    sk = k.shape[1]
    offset = sk - sq
    nq, nk = sq // block_q, sk // block_k
    grid = (bh, nq, nk)

    def kv_index(b, qi, ki):
        return (b // group, ki, 0)

    o_t, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, d, block_q), lambda b, qi, ki: (b, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, d, sq), q.dtype),      # transposed
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),  # lse
        ],
        scratch_shapes=[
            pltpu.VMEM((d, block_q), jnp.float32),
            pltpu.VMEM((8, block_q), jnp.float32),
            pltpu.VMEM((8, block_q), jnp.float32),
        ],
        interpret=_interpret_default(),
    )(q, k, v)
    return jnp.swapaxes(o_t, 1, 2), lse


def _flash_bwd(q, k, v, o, lse, do, dlse, scale, causal, block_q, block_k,
               group):
    """Shared backward. dlse folds into the delta row constant:
    ds = p * (dp - delta + dlse)  (d lse_i / d s_ij = p_ij)."""
    _ensure_pallas()
    bh, sq, d = q.shape
    bhkv, sk = k.shape[0], k.shape[1]
    offset = sk - sq
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]  # (bh, 1, sq)
    if dlse is not None:
        delta = delta - dlse

    def kv_index_kq(b, ki, qi):
        return (b // group, ki, 0)

    # For group > 1 each q head produces its own dk/dv slice (adjacent
    # programs may not accumulate into one output block), reduced after.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index_kq),
            pl.BlockSpec((1, block_k, d), kv_index_kq),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, ki, qi: (b, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b, ki, qi: (b, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret_default(),
    )(q, k, v, do, lse, delta)
    if group > 1:
        dk = dk.reshape(bhkv, group, sk, d).sum(axis=1)
        dv = dv.reshape(bhkv, group, sk, d).sum(axis=1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (b // group, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (b // group, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, qi, ki: (b, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret_default(),
    )(q, k, v, do, lse, delta)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_flat(q, k, v, scale, causal, block_q, block_k):
    group = q.shape[0] // k.shape[0]
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, group)
    return o


def _flash_flat_fwd(q, k, v, scale, causal, block_q, block_k):
    group = q.shape[0] // k.shape[0]
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, group)
    # Tag the kernel outputs as remat-saveable where the residuals are
    # actually built: under jax.checkpoint with a save_only_these_names
    # policy, tagging AFTER the custom-vjp call would save a copy while
    # the bwd still consumed the untagged residual — re-running the whole
    # forward kernel in the backward pass.
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, o, lse)


def _flash_flat_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    group = q.shape[0] // k.shape[0]
    return _flash_bwd(q, k, v, o, lse, do, None, scale, causal,
                      block_q, block_k, group)


_flash_flat.defvjp(_flash_flat_fwd, _flash_flat_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_flat_with_lse(q, k, v, scale, causal, block_q, block_k, tag):
    group = q.shape[0] // k.shape[0]
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, group)


def _flash_wl_fwd(q, k, v, scale, causal, block_q, block_k, tag):
    group = q.shape[0] // k.shape[0]
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, group)
    if tag:
        # `tag=False` for per-step ring-attention partials: tagging those
        # would make the dots remat policy save every ring step's partial
        # o/lse (xR memory) instead of only the final combined output.
        o = checkpoint_name(o, "attn_out")
        lse = checkpoint_name(lse, "attn_lse")
    return (o, lse), (q, k, v, o, lse)


def _flash_wl_bwd(scale, causal, block_q, block_k, tag, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    group = q.shape[0] // k.shape[0]
    return _flash_bwd(q, k, v, o, lse, do, dlse, scale, causal,
                      block_q, block_k, group)


_flash_flat_with_lse.defvjp(_flash_wl_fwd, _flash_wl_bwd)


def _pick_block(s: int, b: int) -> int:
    """Largest block <= b that divides s (halving); s<=128 is one block."""
    b0, b = b, min(b, s)
    while s % b and b > 128:
        b //= 2
    if s % b:
        raise ValueError(
            f"flash_attention block size {b0} is incompatible with seq "
            f"length {s}: no halving of it >= 128 divides the length")
    return b


def _validate_flash(q, k, causal, block_q, block_k):
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    if (sq > 128 and sq % 128) or (sk > 128 and sk % 128):
        raise ValueError(
            f"flash_attention requires seq lengths divisible by the "
            f"128-lane tile: sq={sq}, sk={sk} "
            f"(pad inputs or use attention_reference)")
    if d % 64:
        raise ValueError(f"head_dim {d} must be a multiple of 64")
    if hq % hkv:
        raise ValueError(f"Hq={hq} must be a multiple of Hkv={hkv}")
    if causal and sq > sk:
        raise ValueError(
            "causal flash attention requires sq <= sk (rows with no "
            "visible keys are ill-defined); use attention_reference")


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Pallas TPU flash attention. q: [B,Hq,Sq,D], k/v: [B,Hkv,Sk,D]."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    _validate_flash(q, k, causal, block_q, block_k)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    qf = q.reshape(b * hq, sq, d)
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    o = _flash_flat(qf, kf, vf, scale, causal, block_q, block_k)
    return o.reshape(b, hq, sq, d)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             scale: Optional[float] = None,
                             block_q: int = DEFAULT_BLOCK_Q,
                             block_k: int = DEFAULT_BLOCK_K,
                             save_residuals: bool = True):
    """Like flash_attention but also returns logsumexp [B,Hq,Sq] —
    differentiable in both outputs (the ring-attention building block).
    `save_residuals=False` skips remat-saveable tagging (ring partials)."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    _validate_flash(q, k, causal, block_q, block_k)
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    qf = q.reshape(b * hq, sq, d)
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    o, lse = _flash_flat_with_lse(qf, kf, vf, scale, causal,
                                  block_q, block_k, save_residuals)
    return (o.reshape(b, hq, sq, d),
            lse.reshape(b, hq, sq))


def attention_reference_with_lse(q, k, v, causal: bool = True,
                                 scale: Optional[float] = None):
    """Reference (o, lse) pair; plain autodiff handles gradients."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    group = hq // hkv
    qg = q.reshape(b, hkv, group, sq, d)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p, v.astype(jnp.float32)
                   ) / l[..., None]
    lse = m + jnp.log(l)
    return (o.reshape(b, hq, sq, d).astype(q.dtype),
            lse.reshape(b, hq, sq))


def _flash_ok(q, k, causal: bool) -> bool:
    sq, sk, d = q.shape[2], k.shape[2], q.shape[3]
    return (sq % 128 == 0 and sk % 128 == 0 and d % 64 == 0
            and q.shape[1] % k.shape[1] == 0
            and not (causal and sq > sk))


def attention(q, k, v, causal: bool = True, scale: Optional[float] = None,
              impl: str = "auto",
              block_q: int = DEFAULT_BLOCK_Q,
              block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Dispatcher: pallas flash on TPU when shapes tile cleanly, else the
    reference path (CPU meshes, ragged shapes, causal sq > sk)."""
    if impl == "reference":
        return attention_reference(q, k, v, causal, scale)
    if impl == "flash":
        return flash_attention(q, k, v, causal, scale, block_q, block_k)
    on_tpu = any(dev.platform == "tpu" for dev in jax.devices())
    if _flash_ok(q, k, causal) and on_tpu:
        return flash_attention(q, k, v, causal, scale, block_q, block_k)
    return attention_reference(q, k, v, causal, scale)


def _tag_saveable(o, lse):
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, lse


def attention_with_lse(q, k, v, causal: bool = True,
                       scale: Optional[float] = None, impl: str = "auto",
                       block_q: int = DEFAULT_BLOCK_Q,
                       block_k: int = DEFAULT_BLOCK_K):
    """(o, lse) dispatcher; outputs are tagged remat-saveable.

    The flash path tags INSIDE the custom-vjp fwd rule: under a
    save_only_these_names policy, tagging after the call would save a
    copy while the bwd still consumed the untagged residual — re-running
    the whole forward kernel in the backward pass just to regenerate lse.
    The reference path has no custom vjp, so tagging here suffices."""
    if impl == "reference":
        return _tag_saveable(*attention_reference_with_lse(
            q, k, v, causal, scale))
    if impl == "flash":
        return flash_attention_with_lse(q, k, v, causal, scale,
                                        block_q, block_k)
    on_tpu = any(dev.platform == "tpu" for dev in jax.devices())
    if _flash_ok(q, k, causal) and on_tpu:
        return flash_attention_with_lse(q, k, v, causal, scale,
                                        block_q, block_k)
    return _tag_saveable(*attention_reference_with_lse(
        q, k, v, causal, scale))
