"""ray_tpu: a TPU-native distributed compute framework.

Public core API — analog of the reference's python/ray/_private/worker.py
surface (init :1260, get :2617, put :2785, wait :2850, remote :3239) with
the same semantics on a TPU-first runtime: tasks and actors over a native
shared-memory object store, plus JAX mesh-native parallel/train/data/serve
layers in the subpackages.
"""

from __future__ import annotations

import atexit
import os
import time
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

# Concurrency sanitizer: must install BEFORE the runtime modules
# below create their module/instance locks, or they escape
# instrumentation.  Env-gated (never config: workers inherit the
# env).  locksan imports stdlib only, so the unconditional import is
# cheap and keeps the flag parse in one place.
from ray_tpu.devtools import locksan as _locksan

if _locksan.enabled():
    _locksan.install()

# Resource-leak ledger (devtools/leaksan.py): same env-gated story as
# locksan — arm the atexit dump here so every process (driver, node,
# worker — the env inherits) leaves a per-pid ledger for `ray_tpu
# leaksan` to merge.  The hooks themselves are compiled into the
# instrumented subsystems and gate on the module flag.
from ray_tpu.devtools import leaksan as _leaksan

if _leaksan.enabled():
    _leaksan.install()

# XLA sanitizer (devtools/xlasan.py): env-gated like the two above.
# install() patches jax.jit at import so every later jit construction
# — in ray_tpu's own train/models/rllib layers AND user code — is
# tracked in the recompile ledger.  Deferred until jax imports
# cleanly; a missing jax just leaves the sanitizer dormant.
from ray_tpu.devtools import xlasan as _xlasan

if _xlasan.enabled():
    _xlasan.install()

from ray_tpu._private.config import config
from ray_tpu import exceptions
from ray_tpu.object_ref import ObjectRef
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.actor import ActorClass, ActorHandle, method

__version__ = "0.1.0"

_session_lock = threading.RLock()
_session: Optional["_Session"] = None


class _Session:
    def __init__(self, node_service, client, session_dir: str,
                 is_worker: bool = False) -> None:
        self.node_service = node_service
        self.client = client
        self.session_dir = session_dir
        self.is_worker = is_worker
        # config-override snapshot to restore at shutdown (None = no
        # _system_config was applied by this session)
        self.prev_config_overrides = None


def _detect_tpu_chips() -> int:
    """TPU chip count (delegates to the accelerator manager,
    _private/accelerators.py — reference: accelerators/tpu.py:107)."""
    from ray_tpu._private.accelerators import detect_num_chips
    return detect_num_chips()


def init(num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "default",
         gcs_address: Optional[tuple] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         ignore_reinit_error: bool = False) -> None:
    """Start the runtime in this process (head node + driver).

    With ``gcs_address=(host, port)`` the node joins an existing cluster
    (its GCS process) as a full member: tasks spill across nodes, objects
    transfer between stores, actors place cluster-wide.

    Reference analog: ray.init local-mode bring-up (worker.py:1260 →
    node.py start_head_processes) — here the node service runs as threads
    in the driver process and workers are child processes.
    """
    global _session
    with _session_lock:
        if _session is not None:
            if ignore_reinit_error:
                return
            raise RuntimeError("ray_tpu.init() called twice "
                               "(pass ignore_reinit_error=True to allow)")
        if _system_config:
            # Session-scoped: shutdown() restores the previous override
            # state, so one session's knobs (e.g. a test's aggressive
            # OOM thresholds) can never leak into the next.
            _prev_overrides = dict(config._overrides)
            config.update(_system_config)
        else:
            _prev_overrides = None
        if gcs_address is None and os.environ.get("RAY_TPU_GCS_ADDRESS"):
            # Injected by job submission (reference: RAY_ADDRESS) so a
            # plain init() inside a job script joins the cluster.
            host, _, port = os.environ["RAY_TPU_GCS_ADDRESS"].rpartition(
                ":")
            gcs_address = (host or "127.0.0.1", int(port))
        from ray_tpu._private.client import CoreClient, set_global_client
        from ray_tpu._private.node_service import NodeService

        session_dir = os.path.join(
            config.session_dir_prefix,
            f"session_{int(time.time()*1000)}_{os.getpid()}")
        os.makedirs(session_dir, exist_ok=True)
        res = dict(resources or {})
        res["CPU"] = float(num_cpus if num_cpus is not None
                           else (os.cpu_count() or 1))
        tpus = float(num_tpus if num_tpus is not None
                     else _detect_tpu_chips())
        if tpus:
            # Typed slice resources + the worker-0 gang marker
            # (reference: accelerators/tpu.py:360-362 "TPU-{type}-head"
            # — exactly one placement group head bundle per slice).
            from ray_tpu._private.accelerators import tpu_resources
            for k, v in tpu_resources(tpus).items():
                res.setdefault(k, v)
            res["TPU"] = tpus
        store_capacity = object_store_memory or config.object_store_memory
        store_path = os.path.join("/dev/shm", f"rtpu_{os.getpid()}_"
                                  f"{int(time.time()*1000) % 100000}")
        node = NodeService(session_dir, res, store_path, store_capacity,
                           gcs_address=gcs_address)
        node.start()
        client = CoreClient(node.socket_path, kind="driver")
        set_global_client(client)
        _session = _Session(node, client, session_dir)
        _session.prev_config_overrides = _prev_overrides
        atexit.register(shutdown)


def shutdown() -> None:
    global _session
    with _session_lock:
        if _session is None:
            return
        # Compiled graphs hold mmap channel files in /dev/shm-backed
        # session space: sweep any the user never tore down (and their
        # actor loop tasks) while the client can still reach the node.
        import sys as _sys
        _dag_mod = _sys.modules.get("ray_tpu.dag")
        if _dag_mod is not None:
            try:
                _dag_mod._teardown_all()
            except Exception:
                pass
        sess, _session = _session, None
        if sess.prev_config_overrides is not None:
            with config._lock:
                config._overrides.clear()
                config._overrides.update(sess.prev_config_overrides)
        from ray_tpu._private.client import set_global_client
        try:
            sess.client.close()
        except Exception:
            pass
        set_global_client(None)
        if sess.node_service is not None:
            sess.node_service.shutdown()
            # Service-side store client handle is a class attribute; reset
            # so a fresh init() reopens the new segment.
            from ray_tpu._private import node_service as ns
            if ns.NodeService._store_client is not None:
                try:
                    ns.NodeService._store_client.close()
                except Exception:
                    pass
                ns.NodeService._store_client = None


def get_runtime_context():
    """Identity/introspection for the current driver/task/actor
    (reference: ray.get_runtime_context)."""
    from ray_tpu.runtime_context import get_runtime_context as _grc
    return _grc()


def is_initialized() -> bool:
    return _session is not None


def _ensure_connected():
    import threading
    with _session_lock:
        if _session is None:
            # Auto-init only from the main thread (ray.get's implicit
            # ray.init semantic).  A background thread that outlived
            # shutdown() — a serve long-poll loop, a done-callback
            # waiter — must fail its call, not silently resurrect a
            # fresh session and break the next init() with
            # "called twice".
            if threading.current_thread() is not threading.main_thread():
                raise RuntimeError(
                    "ray_tpu is not initialized in this process")
            init()
        return _session.client


def _mark_worker_connected(client) -> None:
    """Called by worker_main: adopt the worker's client as this process's
    session so user code can call ray_tpu.* inside tasks."""
    global _session
    with _session_lock:
        _session = _Session(None, client, client.session_dir,
                            is_worker=True)


# ---------------------------------------------------------------------------
# core API
# ---------------------------------------------------------------------------
def remote(*args, **options):
    """@remote decorator for functions and classes."""
    def wrap(obj):
        # Decoration-time lint runs HERE, once per decoration — not in
        # the constructors, which also run on every .options() clone
        # and on worker-side unpickle.
        from ray_tpu.devtools.lint.decoration import (
            check_actor_class, check_remote_function)
        if isinstance(obj, type):
            ac = ActorClass(obj, options)
            check_actor_class(obj)
            return ac
        rf = RemoteFunction(obj, options)
        check_remote_function(obj)
        return rf

    if len(args) == 1 and not options and callable(args[0]):
        return wrap(args[0])
    if args:
        raise TypeError("@remote takes only keyword options")
    return wrap


def put(value: Any) -> ObjectRef:
    return _ensure_connected().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        timeout: Optional[float] = None):
    client = _ensure_connected()
    if isinstance(refs, ObjectRef):
        return client.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError("get() expects an ObjectRef or a list of them, "
                        f"got {type(refs)}")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list must contain ObjectRefs, "
                            f"got {type(r)}")
    return client.get(list(refs), timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    if not isinstance(refs, (list, tuple)) or any(
            not isinstance(r, ObjectRef) for r in refs):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return _ensure_connected().wait(list(refs), num_returns, timeout)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel the task producing `ref` (reference: ray.cancel).
    Pending tasks fail with TaskCancelledError immediately; running
    tasks receive KeyboardInterrupt (or are force-killed); retries do
    not resurrect a cancelled task."""
    _ensure_connected().cancel_task(ref.binary(), force=force)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _ensure_connected().kill_actor(actor._actor_id, no_restart)


def exit_actor() -> None:
    """Terminate the CURRENT actor after this method call completes
    (reference: ray.actor.exit_actor).  The in-flight call returns
    normally (value None); the actor then dies permanently — no
    restart is attempted regardless of max_restarts."""
    from ray_tpu.runtime_context import _current_spec
    spec = _current_spec.get(None)
    if not spec or spec.get("actor_id") is None:
        raise RuntimeError("exit_actor() called outside an actor "
                           "method")
    raise exceptions.ActorExitRequest()


def get_tpu_ids() -> List[int]:
    """Chip ids leased to this worker (reference: ray.get_gpu_ids /
    get_tpu_ids — reads the TPU_VISIBLE_CHIPS pin the node's chip
    allocator exported at worker spawn).  Empty in the driver or on
    unpinned workers."""
    raw = os.environ.get("TPU_VISIBLE_CHIPS", "")
    return [int(c) for c in raw.split(",") if c != ""]


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    client = _ensure_connected()
    reply = client.lookup_named_actor(name, namespace)
    if reply["actor_id"] is None or reply["spec"] is None:
        raise ValueError(f"no actor named {name!r} in namespace "
                         f"{namespace!r}")
    spec = reply["spec"]
    cls = client.fetch_function(spec["class_id"])
    from ray_tpu.actor import _method_meta
    meta = _method_meta(cls) if cls else {}
    return ActorHandle(reply["actor_id"], spec["class_id"],
                       spec.get("name") or "actor", meta)


def list_named_actors(namespace: Optional[str] = None) -> List[str]:
    return _ensure_connected().list_named_actors(namespace)


def cluster_resources() -> Dict[str, float]:
    return _ensure_connected().cluster_resources()["total"]


def available_resources() -> Dict[str, float]:
    return _ensure_connected().cluster_resources()["available"]


def nodes() -> List[dict]:
    """Alive cluster nodes (single-node mode: a one-entry synthetic
    list).  Reference analog: ray.nodes()."""
    reply = _ensure_connected().cluster_resources()
    if "nodes" in reply:
        return reply["nodes"]
    return [{"node_id": b"local", "host": "127.0.0.1", "state": "alive",
             "resources_total": reply["total"],
             "resources_avail": reply["available"]}]


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "put", "get", "wait",
    "kill", "get_actor", "list_named_actors", "cluster_resources",
    "available_resources", "nodes", "method", "ObjectRef", "ActorHandle",
    "exceptions", "__version__",
]
